"""Crash-recoverable engine journal (append-only host-side JSONL).

The serving engine's durability story mirrors the PR-13 checkpoint
protocol, restated for requests instead of weights: every ACCEPTED
request and every token the engine emits is appended to a journal file,
one JSON record per line, flushed once per scheduler iteration. Greedy
decoding is deterministic in (prompt + generated history), so the
journal never needs to capture device state — a fresh engine replays
the journal into its waiting queue (``InferenceEngine.recover``) and
re-drives each unfinished request through the ordinary preempted-
sequence path; tokens emitted after the journal's last flush are simply
re-derived bit-identically.

Record grammar (``ev`` field):

  ``open``     journal opened (version stamp; ``resume`` marks a
               post-recovery reopen)
  ``submit``   an accepted request: rid + everything needed to rebuild
               the ``Request`` (prompt, limits, deadlines, priority)
  ``reject``   an admission rejection, with its cause (audit only —
               rejected requests are never replayed)
  ``tokens``   tokens emitted since the previous record, in emission
               order: ``[[rid, tok], ...]`` (iterations are coalesced)
  ``finish``   rid completed (written AFTER its final ``tokens`` record,
               so a torn tail can lose the finish mark but never a
               finished request's tokens)
  ``shed``/``failed``  terminal non-success outcomes, with cause
  ``swap``     a live weight swap landed (audit)
  ``recover``  a successor engine adopted this journal

A crash can tear the final line; :func:`read_journal` tolerates (and
counts) undecodable lines. Durability: ADMISSION records (submit /
reject) flush on append — an accepted request can never vanish. Token
pairs coalesce in memory and everything else rides the userspace
buffer (drained in order by the next flushed append, a clean ``run()``
exit, or ``close``), because anything lost with the buffer is
re-derived on recovery: tokens bit-identically from greedy replay,
finish/shed/failed marks by re-hitting the same deterministic
condition. Set ``PADDLE_TPU_SERVE_JOURNAL_FSYNC`` for power-failure
durability at an fsync-per-flush cost.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["EngineJournal", "JournalCompatError", "JournalState",
           "read_journal"]

_VERSION = 1


class JournalCompatError(ValueError):
    """A journal cannot be recovered onto THIS engine configuration.

    Raised up front by ``InferenceEngine.recover()`` — before any state
    is touched — when the successor's ``ServeConfig`` breaks the bit-
    identical re-drive contract: a different ``kv_dtype`` (int8 is the
    documented numeric deviation, so crossing it changes streams), or a
    journaled request that can never fit the successor's ``max_seq_len``
    / block pool. Config differences that PARITY.md pins as bit-identical
    (mp degree, prefix caching, speculation) recover freely."""


class EngineJournal:
    """Append-only writer half. One journal belongs to one engine at a
    time; records are single-line JSON, written in logical order but
    SERIALIZED lazily: appends land in an in-memory record buffer
    (token pairs coalesce into the buffer's trailing ``tokens``
    record), and the whole buffer is dumped + written + flushed in one
    batch at each durability point — an admission record, a clean
    ``run()`` exit, ``close``. The per-iteration hot path is a list
    extend; the per-request cost is one ``dict`` construction; the
    syscalls and ``json.dumps`` bill only where durability demands
    them."""

    # backstop cap on buffered records — durability points drain the
    # buffer far sooner in any live engine
    MAX_PENDING = 4096

    def __init__(self, path: str, fsync: bool = False,
                 resume: bool = False,
                 meta: Optional[Dict[str, Any]] = None):
        self.path = path
        self.fsync = bool(fsync)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._buf: List[Dict[str, Any]] = []
        # meta is AUDIT-ONLY engine configuration (e.g. kv_dtype,
        # prefix_cache — PR 16). Cache state itself is derived, never
        # journaled: recovery re-derives identical bytes from the token
        # record, so replay needs no cache snapshot. read_journal
        # ignores unknown open-record fields by construction.
        self._append({"ev": "open", "version": _VERSION,
                      "resume": bool(resume), **(meta or {})})

    def _write_buf(self) -> None:
        if self._buf:
            recs, self._buf = self._buf, []
            self._f.write("".join(
                json.dumps(r, separators=(",", ":")) + "\n"
                for r in recs))

    def _append(self, rec: Dict[str, Any]) -> None:
        """Durable append: everything buffered so far, then ``rec``, hit
        the OS in order (so a finish mark can never outrun its
        request's tokens)."""
        self._buf.append(rec)
        self.flush()

    def _defer(self, rec: Dict[str, Any]) -> None:
        """Buffered append: serialized at the next durability point. A
        deferred record that dies with the process is re-derived on
        recovery (see the class docstring's durability contract)."""
        self._buf.append(rec)
        if len(self._buf) >= self.MAX_PENDING:
            self._write_buf()

    def submit(self, req) -> None:
        self._append({
            "ev": "submit", "rid": int(req.request_id),
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_id": None if req.eos_id is None else int(req.eos_id),
            "arrival": float(req.arrival),
            "priority": int(getattr(req, "priority", 0)),
            "ttft_deadline": getattr(req, "ttft_deadline", None),
            "deadline": getattr(req, "deadline", None),
        })

    def reject(self, rid: int, cause: str) -> None:
        self._append({"ev": "reject", "rid": int(rid), "cause": cause})

    def tokens(self, iteration: int,
               pairs: Iterable[Tuple[int, int]]) -> None:
        # the per-iteration hot path: pairs coalesce into the buffer's
        # trailing tokens record — a list extend, no serialization, no
        # syscall. Tokens that die in the buffer are re-derived
        # bit-identically by recover() (greedy decode is deterministic
        # in prompt + history), so nothing durable is lost.
        toks = [[int(r), int(t)] for r, t in pairs]
        if not toks:
            return
        if self._buf and self._buf[-1].get("ev") == "tokens":
            self._buf[-1]["toks"].extend(toks)
        else:
            self._defer({"ev": "tokens", "toks": toks})

    # finish/shed/failed/swap marks are deferred like tokens: if they
    # die with the process, recover() re-queues the request and the
    # successor re-derives the same outcome (finish via done(), shed/
    # failed by re-hitting the same deadline or poison) — nothing is
    # silently dropped as long as the SUBMIT record was durable

    def finish(self, rid: int) -> None:
        self._defer({"ev": "finish", "rid": int(rid)})

    def shed(self, rid: int, cause: str) -> None:
        self._defer({"ev": "shed", "rid": int(rid), "cause": cause})

    def failed(self, rid: int, cause: str) -> None:
        self._defer({"ev": "failed", "rid": int(rid), "cause": cause})

    def swap(self, iteration: int, source: Optional[str]) -> None:
        self._defer({"ev": "swap", "it": int(iteration),
                     "source": source})

    def recovered(self, n_requests: int, torn_lines: int) -> None:
        self._append({"ev": "recover", "n_requests": int(n_requests),
                      "torn_lines": int(torn_lines)})

    def flush(self) -> None:
        """Serialize the buffer and push everything to the OS — called
        by every durable append and once per clean ``run()`` exit, so
        an idle journal is always complete on disk."""
        self._write_buf()
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def discard_pending(self) -> None:
        """Drop buffered records without writing them. recover() calls
        this on a journal that survived an in-process crash: buffered
        tokens/marks predate the recovery read, and draining them AFTER
        it would duplicate those streams in the file."""
        self._buf = []

    def close(self) -> None:
        if not self._f.closed:
            self.flush()
            self._f.close()

    def abandon(self) -> None:
        """Crash-simulation close: drop the buffered records and close
        the fd WITHOUT flushing — exactly what the OS does to a killed
        process. What dies with the buffer (tokens, finish marks) is
        re-derived by recovery; durable appends already hit the OS.
        Used by the fleet's ``kill_replica`` so a killed replica's
        journal looks like a real crash, torn tail and all."""
        self._buf = []
        if not self._f.closed:
            self._f.close()


@dataclasses.dataclass
class JournalState:
    """Parsed journal: everything a successor engine needs to re-drive."""
    requests: Dict[int, Dict[str, Any]]   # rid -> submit record, in order
    tokens: Dict[int, List[int]]          # rid -> emitted tokens, in order
    finished: Set[int]
    rejected: Dict[int, str]              # rid -> cause
    shed: Dict[int, str]
    failed: Dict[int, str]
    swaps: int = 0
    torn_lines: int = 0
    # the FIRST open record's audit fields (kv_dtype, prefix_cache,
    # speculative, mp) — the configuration that produced the journaled
    # tokens; recover() checks successor compatibility against it
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def terminal_rids(self) -> Set[int]:
        return (self.finished | set(self.shed) | set(self.failed)
                | set(self.rejected))

    def unfinished_rids(self) -> List[int]:
        """Accepted requests with no terminal record, in submit order."""
        term = self.terminal_rids()
        return [rid for rid in self.requests if rid not in term]


def read_journal(path: str) -> JournalState:
    """Parse a journal, tolerating a torn tail: undecodable lines are
    counted in ``torn_lines`` and skipped (a crash mid-``write`` can only
    corrupt trailing data; every intact record stands on its own)."""
    st = JournalState(requests={}, tokens={}, finished=set(),
                      rejected={}, shed={}, failed={})
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                st.torn_lines += 1
                continue
            ev = rec.get("ev")
            if ev == "submit":
                rid = int(rec["rid"])
                st.requests[rid] = rec
                st.tokens.setdefault(rid, [])
            elif ev == "tokens":
                for r, t in rec.get("toks", ()):
                    st.tokens.setdefault(int(r), []).append(int(t))
            elif ev == "finish":
                st.finished.add(int(rec["rid"]))
            elif ev == "reject":
                st.rejected[int(rec["rid"])] = rec.get("cause", "")
            elif ev == "shed":
                st.shed[int(rec["rid"])] = rec.get("cause", "")
            elif ev == "failed":
                st.failed[int(rec["rid"])] = rec.get("cause", "")
            elif ev == "swap":
                st.swaps += 1
            elif ev == "open" and not st.meta:
                # the ORIGINAL writer's configuration; resume reopens
                # append later open records but never shadow the first
                st.meta = {k: v for k, v in rec.items()
                           if k not in ("ev", "version", "resume")}
            # recover records carry no replay state
    return st
