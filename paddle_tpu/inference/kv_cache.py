"""Block-table paged KV cache: host-side allocator + device pools.

The vLLM PagedAttention memory model (Kwon et al., SOSP '23) restated
for TPU static shapes: the device holds ONE preallocated pool per
layer-stacked k/v ([L, num_blocks, KV*HD, block_size], see
models/llama.py init_paged_kv_pool), sequences own disjoint sets of
blocks named by per-sequence int32 block tables, and every alloc/free
decision happens HERE on the host — the device path never reshapes,
never compacts, never copies a cache.

Block 0 is the reserved NULL block: it is never allocated, every
padding row of a bucketed decode batch points its whole table at it,
and the fused update kernel scribbles padding rows' (masked) garbage
columns there. That keeps the kernel total — every row writes — while
live blocks stay bit-exact.

PR 16 grows two things on top of the plain free list:

  - **Per-block ref counts**: a block may be owned by several
    sequences at once (copy-on-write prefix sharing). ``free()`` on a
    block with refs > 1 decrements instead of returning it to the free
    list; a double-decrement raises :class:`BlockPoolError` before
    mutating anything; ``used_blocks`` counts a shared block ONCE.
  - **A cached-LRU parking lot**: a block registered in the
    :class:`PrefixCache` whose ref count drops to zero is PARKED
    (kept byte-intact for future prefix hits) instead of freed.
    ``alloc()`` drains the true free list first and only then reclaims
    parked blocks oldest-first — caching never steals capacity from
    live sequences, it only recycles blocks nobody references.
"""
from __future__ import annotations

from collections import Counter, OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class BlockPoolError(ValueError):
    """A caller violated the pool's ownership contract: double free,
    out-of-range id, or the reserved null block. Subclasses ValueError
    so pre-existing ``except ValueError`` callers keep working."""


class BlockPool:
    """Ref-counted free-list allocator over ``num_blocks`` fixed-size
    blocks.

    O(1) alloc/free via a LIFO free list; all-or-nothing allocation so
    a failed admission never leaks partial sets. Block 0 is reserved
    (the null block) and never handed out; ``free()`` validates every
    id — including duplicates WITHIN one call — before mutating
    anything, so a rejected free leaves the pool untouched.

    Blocks marked cache-resident (``mark_cached``, driven by the
    PrefixCache) park in an LRU dict when their last reference drops;
    ``reclaim_cb`` fires when ``alloc()`` repurposes a parked block so
    the index can forget it."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"BlockPool needs >= 2 blocks (one is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1 or block_size % 128:
            raise ValueError(
                f"block_size must be a positive multiple of 128 (TPU lane "
                f"tiling), got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO keeps recently-freed (cache-warm) blocks in circulation
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._free_set = set(self._free)
        self._refs: Dict[int, int] = {}            # live blocks only
        self._cached: "OrderedDict[int, None]" = OrderedDict()  # LRU->MRU
        self._cache_flag: set = set()              # prefix-index members
        self.reclaim_cb: Optional[Callable[[int], None]] = None

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Parked prefix-cache blocks: zero refs, byte-intact, reclaimed
        LRU-oldest-first only after the free list runs dry."""
        return len(self._cached)

    @property
    def available_blocks(self) -> int:
        """Blocks an ``alloc()`` can hand out right now (free + parked)."""
        return len(self._free) + len(self._cached)

    @property
    def used_blocks(self) -> int:
        """Blocks with at least one live reference — a block shared by
        N sequences counts ONCE (the leak audit's contract)."""
        return (self.num_blocks - 1) - len(self._free) - len(self._cached)

    @property
    def utilization(self) -> float:
        return self.used_blocks / max(self.num_blocks - 1, 1)

    def ref_count(self, block: int) -> int:
        return self._refs.get(block, 0)

    def is_registered(self, block: int) -> bool:
        """True while ``block`` backs a PrefixCache entry (live or
        parked)."""
        return block in self._cache_flag

    def can_alloc(self, n: int) -> bool:
        return n <= self.available_blocks

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None (and no state change) if the pool is dry.
        Drains the free list first; then reclaims parked cache blocks
        oldest-first, notifying ``reclaim_cb`` for each so the prefix
        index drops the reclaimed entry."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.available_blocks:
            return None
        take = min(n, len(self._free))
        got = self._free[len(self._free) - take:] if take else []
        del self._free[len(self._free) - take:]
        self._free_set.difference_update(got)
        while len(got) < n:
            b, _ = self._cached.popitem(last=False)   # LRU-oldest
            self._cache_flag.discard(b)
            if self.reclaim_cb is not None:
                self.reclaim_cb(b)
            got.append(b)
        for b in got:
            self._refs[b] = 1
        return got

    def acquire(self, blocks: Sequence[int]) -> None:
        """Take one reference on each block of a prefix-cache hit: a
        parked block comes back live (refs=1, still index-registered),
        a live block's count increments. Validates every id before
        mutating anything."""
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise BlockPoolError(f"acquire of out-of-range block {b}")
            if b not in self._refs and b not in self._cached:
                raise BlockPoolError(
                    f"acquire of free block {b} (not live or parked)")
        for b in blocks:
            if b in self._cached:
                del self._cached[b]
                self._refs[b] = self._refs.get(b, 0) + 1
            else:
                self._refs[b] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per listed block. A block's LAST reference
        either parks it (if prefix-registered) or returns it to the free
        list. Every id — including duplicates within this call — is
        validated against the live ref counts BEFORE anything mutates,
        so a rejected free leaves the pool untouched."""
        counts = Counter(blocks)
        for b, n in counts.items():
            if b == 0:
                raise BlockPoolError(
                    "free of the reserved null block 0")
            if not 1 <= b < self.num_blocks:
                raise BlockPoolError(f"free of out-of-range block {b}")
            if self._refs.get(b, 0) < n:
                raise BlockPoolError(f"double free of block {b}")
        for b, n in counts.items():
            left = self._refs[b] - n
            if left:
                self._refs[b] = left
            else:
                del self._refs[b]
                if b in self._cache_flag:
                    self._cached[b] = None           # park at MRU end
                else:
                    self._free.append(b)
                    self._free_set.add(b)

    def mark_cached(self, block: int) -> None:
        """Flag a LIVE block as prefix-cache-resident: when its last
        reference drops it parks instead of freeing."""
        if self._refs.get(block, 0) < 1:
            raise BlockPoolError(
                f"mark_cached of non-live block {block}")
        self._cache_flag.add(block)

    def unmark_cached(self, block: int) -> None:
        """Withdraw a block from cache residency (index invalidation).
        A parked block goes straight back to the free list."""
        self._cache_flag.discard(block)
        if block in self._cached:
            del self._cached[block]
            self._free.append(block)
            self._free_set.add(block)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` occupies."""
        return -(-max(n_tokens, 0) // self.block_size)


class PrefixCache:
    """Token-exact prefix index over a :class:`BlockPool`.

    Maps the EXACT cumulative token tuple of each full block —
    ``tuple(tokens[:i * block_size])`` — to the pool block holding its
    KV bytes. Exact tuples (not hashes) rule out collision reuse of
    wrong-token blocks; memory is bounded by the pool itself since an
    entry dies with its block's reclaim. ``match`` walks the longest
    chain of consecutive full-block keys; the engine acquires those
    blocks (copy-on-write — see InferenceEngine._cow_span) and skips
    prefill for the hit span.

    Cache state is DERIVED, never journaled: a block's bytes are a
    deterministic function of its token prefix (greedy decode + the
    per-column quantizer), so recovery re-deriving from the journal is
    bit-identical whether a prefix hit or a cold prefill produced them.
    """

    def __init__(self, pool: BlockPool):
        self.pool = pool
        pool.reclaim_cb = self._on_reclaim
        self._index: Dict[Tuple[int, ...], int] = {}
        self._owner: Dict[int, Tuple[int, ...]] = {}
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.registered = 0
        self.reclaimed = 0
        self.invalidated = 0

    def __len__(self) -> int:
        return len(self._index)

    def _keys(self, tokens: Sequence[int], limit_blocks: int
              ) -> List[Tuple[int, ...]]:
        bs = self.pool.block_size
        n = min(int(limit_blocks), len(tokens) // bs)
        return [tuple(int(t) for t in tokens[:i * bs])
                for i in range(1, n + 1)]

    def match(self, tokens: Sequence[int], limit_blocks: int
              ) -> List[int]:
        """Longest chain of cached full blocks prefixing ``tokens``
        (block-aligned; at most ``limit_blocks``). Counts stats; the
        caller still owns nothing until it ``acquire``s the result."""
        self.lookups += 1
        blocks: List[int] = []
        for key in self._keys(tokens, limit_blocks):
            b = self._index.get(key)
            if b is None:
                break
            blocks.append(b)
        if blocks:
            self.hits += 1
            self.hit_tokens += len(blocks) * self.pool.block_size
        return blocks

    def match_len(self, tokens: Sequence[int], limit_blocks: int,
                  pending: Optional[set] = None) -> int:
        """Stat-free match length for admission estimates; ``pending``
        holds prospective keys of not-yet-prefilled queued prompts, so
        a same-instant burst of identical prompts already counts as
        shared."""
        n = 0
        for key in self._keys(tokens, limit_blocks):
            if key in self._index or (pending is not None
                                      and key in pending):
                n += 1
            else:
                break
        return n

    def prospective_keys(self, tokens: Sequence[int],
                         limit_blocks: int) -> List[Tuple[int, ...]]:
        """The full-block keys ``tokens`` WILL register once prefilled
        (admission-estimate helper)."""
        return self._keys(tokens, limit_blocks)

    def register(self, tokens: Sequence[int], blocks: Sequence[int],
                 n_blocks: int) -> int:
        """Index ``blocks[:n_blocks]`` under the cumulative keys of
        ``tokens``. First writer wins per key (a concurrent identical
        prompt's private blocks simply stay unregistered); a block
        already owning a different key is skipped. Returns entries
        added."""
        added = 0
        for i, key in enumerate(self._keys(tokens, n_blocks)):
            b = int(blocks[i])
            if key in self._index or b in self._owner:
                continue
            self._index[key] = b
            self._owner[b] = key
            self.pool.mark_cached(b)
            self.registered += 1
            added += 1
        return added

    def invalidate_block(self, block: int) -> None:
        """Drop the entry backed by ``block`` (engine COW guard: a
        write into a registered ref-1 block would corrupt the index's
        bytes, so the entry is forgotten instead)."""
        key = self._owner.pop(block, None)
        if key is not None:
            self._index.pop(key, None)
            self.invalidated += 1
        self.pool.unmark_cached(block)

    def _on_reclaim(self, block: int) -> None:
        key = self._owner.pop(block, None)
        if key is not None:
            self._index.pop(key, None)
            self.reclaimed += 1

    def stats(self) -> Dict[str, float]:
        return {
            "entries": len(self._index),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": self.hits / self.lookups if self.lookups else 0.0,
            "hit_tokens": self.hit_tokens,
            "registered": self.registered,
            "reclaimed": self.reclaimed,
            "invalidated": self.invalidated,
        }


def pad_table(blocks: List[int], max_nb: int) -> np.ndarray:
    """A sequence's block list as a fixed-width table row; unallocated
    slots point at the null block."""
    if len(blocks) > max_nb:
        raise ValueError(
            f"sequence holds {len(blocks)} blocks > table width {max_nb}")
    row = np.zeros((max_nb,), np.int32)
    row[:len(blocks)] = blocks
    return row


def pool_bytes_per_rank(pools: Sequence, mp: int = 1) -> int:
    """Device bytes ONE rank holds for the given KV/scale pools.

    Under tensor-parallel serving (PR 19) every pool shards its
    kv-head-major axis evenly across ``mp`` ranks — the engine
    validates ``num_key_value_heads % mp == 0`` at init, so the split
    is exact and per-rank bytes are total/mp. ``None`` entries (absent
    scale/draft pools) are skipped; ``mp=1`` is just the total."""
    total = 0
    for p in pools:
        if p is None:
            continue
        total += int(p.size) * int(np.dtype(p.dtype).itemsize)
    return total // max(1, int(mp))
