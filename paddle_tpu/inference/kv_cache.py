"""Block-table paged KV cache: host-side allocator + device pools.

The vLLM PagedAttention memory model (Kwon et al., SOSP '23) restated
for TPU static shapes: the device holds ONE preallocated pool per
layer-stacked k/v ([L, num_blocks, KV*HD, block_size], see
models/llama.py init_paged_kv_pool), sequences own disjoint sets of
blocks named by per-sequence int32 block tables, and every alloc/free
decision happens HERE on the host — the device path never reshapes,
never compacts, never copies a cache.

Block 0 is the reserved NULL block: it is never allocated, every
padding row of a bucketed decode batch points its whole table at it,
and the fused update kernel scribbles padding rows' (masked) garbage
columns there. That keeps the kernel total — every row writes — while
live blocks stay bit-exact.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class BlockPoolError(ValueError):
    """A caller violated the pool's ownership contract: double free,
    out-of-range id, or the reserved null block. Subclasses ValueError
    so pre-existing ``except ValueError`` callers keep working."""


class BlockPool:
    """Free-list allocator over ``num_blocks`` fixed-size blocks.

    O(1) alloc/free via a LIFO free list (with a set mirror for O(1)
    double-free detection); all-or-nothing allocation so a failed
    admission never leaks partial sets. Block 0 is reserved (the null
    block) and never handed out; ``free()`` validates every id —
    including duplicates WITHIN one call — before mutating anything, so
    a rejected free leaves the pool untouched."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"BlockPool needs >= 2 blocks (one is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1 or block_size % 128:
            raise ValueError(
                f"block_size must be a positive multiple of 128 (TPU lane "
                f"tiling), got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO keeps recently-freed (cache-warm) blocks in circulation
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._free_set = set(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def utilization(self) -> float:
        return self.used_blocks / max(self.num_blocks - 1, 1)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None (and no state change) if the pool is dry."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        self._free_set.difference_update(got)
        return got

    def free(self, blocks: List[int]) -> None:
        seen = set()
        for b in blocks:
            if b == 0:
                raise BlockPoolError(
                    "free of the reserved null block 0")
            if not 1 <= b < self.num_blocks:
                raise BlockPoolError(f"free of out-of-range block {b}")
            if b in self._free_set or b in seen:
                raise BlockPoolError(f"double free of block {b}")
            seen.add(b)
        self._free.extend(blocks)
        self._free_set.update(blocks)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` occupies."""
        return -(-max(n_tokens, 0) // self.block_size)


def pad_table(blocks: List[int], max_nb: int) -> np.ndarray:
    """A sequence's block list as a fixed-width table row; unallocated
    slots point at the null block."""
    if len(blocks) > max_nb:
        raise ValueError(
            f"sequence holds {len(blocks)} blocks > table width {max_nb}")
    row = np.zeros((max_nb,), np.int32)
    row[:len(blocks)] = blocks
    return row
