"""Dataset / DataLoader (ref: python/paddle/io/).

The reference prefetches via multi-process workers feeding a C++ blocking
queue. Here: worker threads fill a bounded queue (numpy collate releases the
GIL for the heavy copies); batches convert to device Tensors on the consumer
side so host→HBM transfer overlaps the train step. The queue is backed by the
native runtime's lock-free ring when available (runtime/, csrc/).
"""
from __future__ import annotations

import itertools
import math
import queue as _queue
import threading

import numpy as np

from ..tensor.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        total = len(dataset)
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sliced batch sampler (ref: python/paddle/io/dataloader/batch_sampler.py).

    On TPU SPMD one process usually feeds the whole global batch; per-host
    slicing for multi-host uses num_replicas = process count.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        from ..tensor import stack
        return stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _prefetch_worker(q, idx_q, dataset, collate):
    while True:
        try:
            idxs = idx_q.get_nowait()
        except _queue.Empty:
            return
        samples = [dataset[i] for i in idxs]
        try:
            q.push(collate(samples), timeout=-1.0)
        except RuntimeError:  # consumer closed the queue mid-epoch
            return


class _DataLoaderIter:
    """Prefetching iterator.  With num_workers > 0, worker threads collate
    batches and feed the native runtime's C++ blocking queue (backpressure and
    blocking happen off-GIL; ref: the reader BlockingQueue the reference's
    DataLoader feeds through paddle/fluid/operators/reader/)."""

    def __init__(self, loader):
        from .. import runtime as _rt
        self.loader = loader
        self.batch_iter = iter(loader.batch_sampler)
        self.collate = loader.collate_fn or default_collate_fn
        self.dataset = loader.dataset
        if loader.num_workers > 0:
            self.q = _rt.BlockingQueue(
                capacity=max(2, loader.prefetch_factor * loader.num_workers))
            self.idx_q = _queue.Queue()
            for b in self.batch_iter:
                self.idx_q.put(b)
            self.n_batches = self.idx_q.qsize()
            self.n_got = 0
            # Workers capture only what they need — never `self` — so an
            # abandoned iterator stays collectible; __del__ then closes the
            # queue, which unblocks any worker stuck in push().
            self.workers = [
                threading.Thread(
                    target=_prefetch_worker,
                    args=(self.q, self.idx_q, self.dataset, self.collate),
                    daemon=True)
                for _ in range(loader.num_workers)]
            for w in self.workers:
                w.start()

    def __next__(self):
        if self.loader.num_workers > 0:
            if self.n_got >= self.n_batches:
                self.q.close()
                raise StopIteration
            self.n_got += 1
            return self.q.pop(timeout=-1.0)
        idxs = next(self.batch_iter)
        samples = [self.dataset[i] for i in idxs]
        return self.collate(samples)

    def __iter__(self):
        return self

    def __del__(self):
        if getattr(self, "q", None) is not None:
            try:
                self.q.close()
            except Exception:
                pass


class _IterableLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)
        self.collate = loader.collate_fn or default_collate_fn

    def __next__(self):
        batch = list(itertools.islice(self.it, self.loader.batch_size))
        if not batch:
            raise StopIteration
        if self.loader.drop_last and len(batch) < self.loader.batch_size:
            raise StopIteration
        return self.collate(batch)

    def __iter__(self):
        return self


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        self._mp_pool = None
        self._iterable = isinstance(dataset, IterableDataset)
        if not self._iterable:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                                  batch_size=batch_size,
                                                  drop_last=drop_last)

    def __iter__(self):
        if self._iterable:
            return _IterableLoaderIter(self)
        if self.num_workers > 0 and self.use_shared_memory:
            # PROCESS workers + shared-memory batch transport (reference
            # parity for Python-heavy __getitem__ that threads can't speed
            # up). Children must stay jax-free: use numpy-producing datasets
            # here (TensorDataset slices jax arrays — keep it on threads).
            from .multiprocess import MultiprocessLoaderIter, _WorkerPool
            if self.persistent_workers:
                if self._mp_pool is None or self._mp_pool.closed:
                    self._mp_pool = _WorkerPool(self)
                return MultiprocessLoaderIter(self, pool=self._mp_pool)
            return MultiprocessLoaderIter(self)
        return _DataLoaderIter(self)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)


def get_worker_info():
    return None


class SubsetRandomSampler(Sampler):
    """Random order over a fixed index subset (ref: io.SubsetRandomSampler)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        order = np.random.permutation(len(self.indices))
        return iter(self.indices[i] for i in order)

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    """ref: io.ConcatDataset — concatenation of map-style datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset expects at least one dataset")
        self._sizes = [len(d) for d in self.datasets]

    def __len__(self):
        return sum(self._sizes)

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if idx < 0:
            raise IndexError("ConcatDataset index out of range")
        for d, n in zip(self.datasets, self._sizes):
            if idx < n:
                return d[idx]
            idx -= n
        raise IndexError("ConcatDataset index out of range")
