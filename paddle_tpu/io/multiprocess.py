"""Multi-process DataLoader workers with shared-memory batch transport.

Ref: python/paddle/io/dataloader/dataloader_iter.py (_DataLoaderIterMultiProcess)
+ the reference's shared-memory LoDTensor transport (core._convert_to_shared_
memory). TPU-native constraints shape the design:

- worker processes come from a **forkserver**: the server is fork+exec'd
  with a clean address space, so workers never inherit the parent's live
  jax/XLA/grpc threads or locks (plain `fork` after the TPU backend has
  initialized deadlocks in the child on inherited mutexes — observed on
  this image with the axon tunnel). The server imports the package once;
  each worker is then a cheap fork of that clean, warm process.
- workers run pure numpy (sample fetch + collate). Device Tensors are
  built on the consumer side, so host->HBM transfer stays in the parent.
- batches cross the process boundary as multiprocessing.shared_memory
  segments (one per array leaf); only tiny (name, shape, dtype) metadata
  goes through the result queue. The consumer copies each leaf out of the
  segment exactly once (into the device buffer) and unlinks it.
- a reorder buffer keeps batch order deterministic regardless of which
  worker finishes first (reference behavior).

The thread-based path (io/__init__.py) remains the default for
numpy-collate datasets; process workers win when __getitem__ holds the GIL
(Python-heavy decode/augment), which is exactly the reference's use case
for multi-process loading. Dataset / worker_init_fn must be picklable
(same contract as the reference's multi-process mode).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue as _queue
import sys
from multiprocessing import shared_memory

import numpy as np

_SENTINEL = "__stop__"


def _np_collate(batch):
    """default_collate, but producing numpy leaves only (no jax in
    workers)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return type(sample)(_np_collate(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return batch


def _pack(x, shms):
    """numpy leaf -> shm descriptor (appending the segment to shms)."""
    if isinstance(x, np.ndarray) and x.nbytes > 0:
        x = np.ascontiguousarray(x)
        shm = shared_memory.SharedMemory(create=True, size=x.nbytes)
        dst = np.ndarray(x.shape, x.dtype, buffer=shm.buf)
        dst[...] = x
        del dst
        shms.append(shm)
        return ("shm", shm.name, x.shape, x.dtype.str)
    if isinstance(x, np.ndarray):
        return ("arr", x)
    if isinstance(x, (list, tuple)):
        return ("seq", type(x).__name__, [_pack(v, shms) for v in x])
    if isinstance(x, dict):
        return ("map", {k: _pack(v, shms) for k, v in x.items()})
    return ("val", x)


def _unpack(desc, wrap_leaf, owned):
    """shm descriptor -> pytree. wrap_leaf gets an OWNED (copied) ndarray;
    segments are recorded in `owned` for the caller to unlink."""
    kind = desc[0]
    if kind == "shm":
        _, name, shape, dtype = desc
        shm = shared_memory.SharedMemory(name=name)
        owned.append(shm)
        view = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf)
        arr = view.copy()  # detach from the segment before it is unlinked
        del view
        return wrap_leaf(arr)
    if kind == "arr":
        return wrap_leaf(desc[1])
    if kind == "seq":
        _, tname, items = desc
        vals = [_unpack(v, wrap_leaf, owned) for v in items]
        return tuple(vals) if tname == "tuple" else vals
    if kind == "map":
        return {k: _unpack(v, wrap_leaf, owned) for k, v in desc[1].items()}
    return desc[1]


def _release(owned):
    for shm in owned:
        try:
            shm.close()
            shm.unlink()
        except Exception:
            pass


def _worker_loop(dataset, idx_q, out_q, collate_in_worker, worker_id,
                 worker_init_fn, seed):
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = idx_q.get()
        if item == _SENTINEL:
            out_q.put(_SENTINEL)
            return
        epoch, batch_idx, idxs = item
        try:
            samples = [dataset[i] for i in idxs]
            payload = _np_collate(samples) if collate_in_worker else samples
            shms = []
            desc = _pack(payload, shms)
            out_q.put((epoch, batch_idx, desc, None))
            # segment ownership moves to the consumer, which unlinks after
            # copying out. The shared resource tracker (forkserver children
            # inherit the parent's) keeps the registration until then.
            for shm in shms:
                shm.close()
        except BaseException as e:  # surface dataset errors to the consumer
            out_q.put((epoch, batch_idx, None, f"{type(e).__name__}: {e}"))


_mp_ctx = None


def _get_ctx():
    """forkserver context, created once. The server process has a clean
    address space (fork+exec) and imports this package before serving, so
    worker forks are cheap and jax-state-free.

    The server inherits sys.path via PYTHONPATH (exported here for the
    ensure_running call): without it, paths added at runtime (pytest
    rootdir, site hooks) are invisible to the server, its preload fails
    silently, and every worker re-pays the full framework import."""
    global _mp_ctx
    if _mp_ctx is None:
        try:
            ctx = mp.get_context("forkserver")
            ctx.set_forkserver_preload(["paddle_tpu.io.multiprocess"])
            from multiprocessing import forkserver as _fs
            old = os.environ.get("PYTHONPATH")
            os.environ["PYTHONPATH"] = os.pathsep.join(
                p for p in sys.path if p)
            try:
                _fs._forkserver.ensure_running()
            finally:
                if old is None:
                    os.environ.pop("PYTHONPATH", None)
                else:
                    os.environ["PYTHONPATH"] = old
        except ValueError:  # platform without forkserver
            ctx = mp.get_context("spawn")
        _mp_ctx = ctx
    return _mp_ctx


class _WorkerPool:
    """Process-worker pool + queues. Owned by one iterator (non-persistent)
    or cached on the DataLoader across epochs (persistent_workers=True,
    reference semantics: worker start + module import cost paid once)."""

    def __init__(self, loader):
        from collections import deque
        ctx = _get_ctx()
        nw = loader.num_workers
        # indices are dispatched incrementally with an outstanding cap
        # (reference behavior): bounds idx-queue memory on huge datasets,
        # caps live shm segments, and means an abandoned epoch wastes at
        # most `cap` stale batches of worker time, not the whole epoch
        self.cap = max(2, loader.prefetch_factor * nw)
        self.idx_q = ctx.Queue()
        self.out_q = ctx.Queue(maxsize=self.cap)
        self.feed = deque()
        self.outstanding = 0
        seed = int.from_bytes(os.urandom(4), "little")
        self.workers = [
            ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.idx_q, self.out_q,
                      loader.collate_fn is None, w,
                      getattr(loader, "worker_init_fn", None), seed),
                daemon=True)
            for w in range(nw)]
        for w in self.workers:
            w.start()
        self.epoch = -1
        self.closed = False

    def submit_epoch(self, batches):
        from collections import deque
        self.epoch += 1
        # un-dispatched remainder of an abandoned epoch is simply dropped
        self.feed = deque((self.epoch, i, b) for i, b in enumerate(batches))
        self._fill()
        return self.epoch

    def _fill(self):
        while self.feed and self.outstanding < self.cap:
            self.idx_q.put(self.feed.popleft())
            self.outstanding += 1

    def on_result(self):
        """One outstanding batch was received (any epoch); dispatch more."""
        self.outstanding -= 1
        self._fill()

    def alive(self):
        return any(w.is_alive() for w in self.workers)

    def drain(self, block=False):
        """Pop and free any queued results (stale epochs / shutdown)."""
        try:
            while True:
                item = self.out_q.get(timeout=0.2) if block \
                    else self.out_q.get_nowait()
                if item != _SENTINEL and item[2] is not None:
                    owned = []
                    _unpack(item[2], lambda a: None, owned)
                    _release(owned)
        except _queue.Empty:
            pass

    def shutdown(self):
        if self.closed:
            return
        self.closed = True
        # graceful first: sentinels let workers finish their current batch
        # and exit cleanly (no mid-_pack orphaned shm segments); drain keeps
        # the bounded out_q moving so blocked put()s can complete
        for _ in self.workers:
            self.idx_q.put(_SENTINEL)
        deadline = 10  # drain rounds of 0.2s each
        while deadline > 0 and any(w.is_alive() for w in self.workers):
            self.drain(block=True)
            deadline -= 1
        for w in self.workers:
            if w.is_alive():
                w.terminate()
        for w in self.workers:
            w.join(timeout=5)
        self.drain()  # anything flushed between drain and terminate

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class MultiprocessLoaderIter:
    """In-order iterator over process workers (see module docstring)."""

    def __init__(self, loader, pool=None):
        self.loader = loader
        self.collate = loader.collate_fn  # None => numpy collate in worker
        self.owns_pool = pool is None
        self.pool = pool if pool is not None else _WorkerPool(loader)
        batches = list(iter(loader.batch_sampler))
        self.n_batches = len(batches)
        self.epoch = self.pool.submit_epoch(batches)
        self.next_idx = 0
        self.buffer = {}
        self.done = False
        self.timeout = getattr(loader, "timeout", 0) or 0

    def __iter__(self):
        return self

    def _get_result(self):
        """out_q.get that can never hang forever: polls worker liveness and
        honors the loader's timeout (0 => only die when workers do)."""
        waited = 0.0
        while True:
            try:
                return self.pool.out_q.get(timeout=2.0)
            except _queue.Empty:
                waited += 2.0
                if self.timeout and waited >= self.timeout:
                    self._finish(kill=True)
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s waiting "
                        "for a worker batch")
                if not self.pool.alive():
                    try:  # drain anything flushed between checks
                        return self.pool.out_q.get(timeout=1.0)
                    except _queue.Empty:
                        self._finish(kill=True)
                        raise RuntimeError(
                            "DataLoader workers exited unexpectedly "
                            "(killed or crashed without reporting)")

    def __next__(self):
        from ..tensor.tensor import Tensor
        if self.done or self.next_idx >= self.n_batches:
            self._finish()
            raise StopIteration
        while self.next_idx not in self.buffer:
            item = self._get_result()
            if item == _SENTINEL:  # a worker exited (shutdown elsewhere)
                self._finish(kill=True)
                raise RuntimeError("DataLoader worker pool was shut down")
            self.pool.on_result()  # frees a dispatch slot, feeds the next
            epoch, idx, desc, err = item
            if epoch != self.epoch:  # stale batch from an abandoned epoch
                if desc is not None:
                    owned = []
                    _unpack(desc, lambda a: None, owned)
                    _release(owned)
                continue
            self.buffer[idx] = (desc, err)
        desc, err = self.buffer.pop(self.next_idx)
        self.next_idx += 1
        if err is not None:
            self._finish(kill=True)
            raise RuntimeError(f"DataLoader worker failed: {err}")
        owned = []
        if self.collate is None:
            # worker already collated to numpy; leaves become Tensors here
            out = _unpack(desc, Tensor, owned)
        else:
            # custom collate runs on the consumer (jax-safe) over the raw
            # worker-fetched samples
            samples = _unpack(desc, lambda a: a, owned)
            out = self.collate(samples)
        _release(owned)
        return out

    def _finish(self, kill=False):
        if self.done:
            return
        self.done = True
        for desc, _err in self.buffer.values():
            if desc is not None:
                owned = []
                _unpack(desc, lambda a: None, owned)
                _release(owned)
        self.buffer.clear()
        if self.owns_pool or kill:
            self.pool.shutdown()
            if not self.owns_pool:  # persistent pool died: loader re-creates
                loader_pool = getattr(self.loader, "_mp_pool", None)
                if loader_pool is self.pool:
                    self.loader._mp_pool = None

    # legacy/test hook: shut everything down regardless of pool ownership
    def _shutdown(self):
        self._finish(kill=True)

    @property
    def workers(self):
        return self.pool.workers

    def __del__(self):
        try:
            self._finish()
        except Exception:
            pass
