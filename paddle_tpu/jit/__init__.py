"""Compilation: dygraph -> XLA (ref: python/paddle/jit/)."""
import os as _os

from .functional import TracedLayer, functional_call, state_arrays, to_static
from .train_step import TrainStep


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: persist params + a note that compilation is
    trace-on-load (XLA has no stable serialized program format across
    versions; params + code are the artifact)."""
    from ..framework.io import save as _save
    from ..nn.layer.layers import Layer
    target = layer.layer if isinstance(layer, TracedLayer) else layer
    _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
    _save(target.state_dict(), path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as _load
    return _load(path + ".pdparams")


def not_to_static(fn):
    return fn


def ignore_module(modules):
    return None


def enable_to_static(flag: bool):
    return None
