"""Compilation: dygraph -> XLA (ref: python/paddle/jit/)."""
import os as _os

from . import dy2static
from .dy2static import convert_to_static
from .functional import TracedLayer, functional_call, state_arrays, to_static
from .save_load import TranslatedLayer, load, save
from .train_step import TrainStep


def not_to_static(fn):
    return fn


def ignore_module(modules):
    return None


def enable_to_static(flag: bool):
    return None
