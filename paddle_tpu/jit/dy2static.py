"""Dynamic-to-static control-flow translation (dy2static).

Ref: python/paddle/jit/dy2static — the reference rewrites Python AST so
`if`/`while`/`for` over tensor values become ProgramDesc control-flow ops
(cond/while_op), falling back to plain Python when the predicate is a host
value. TPU-native equivalent: the same AST pass, but the targets are XLA's
structured control flow — `lax.cond`, `lax.while_loop`, `lax.fori_loop` —
selected AT RUNTIME by whether the predicate is a jax tracer:

- eager call / concrete predicate  -> plain Python branch/loop (zero cost)
- under jit tracing, tensor pred   -> lax.cond / lax.while_loop

The transform:
  if c:  A            _t, _f = (lifted branch fns over assigned vars)
  else:  B      ->    vars = _jst.convert_ifelse(c, _t, _f, vars)

  while c: A    ->    vars = _jst.convert_while(cond_fn, body_fn, vars)

  for i in range(n): A  ->  vars = _jst.convert_for_range(n, body_fn, vars)

`break`/`continue` on tensor predicates translate via the reference's flag
rewriting when they appear in the structured form `if <pred>: [stmts...];
break|continue` directly in the loop body: the escape becomes a loop-carried
flag, subsequent statements are gated on it, and a rewritten `while` folds
`and not flag` into its condition (a rewritten `for` runs its full trip
count with a no-op gated body). A trailing `if <pred>: return a` +
`return b` becomes a select. Any other tensor-dependent escape raises
Dy2StaticUnsupportedError with guidance (NOT jax's raw concretization
error); host-value predicates always keep plain Python semantics.

CAUTION (select semantics): a traced `if` runs BOTH branches and selects
the outputs. Pure tensor computation is safe; a branch with side effects
(list.append, print, host I/O, .item()) executes on both paths — the
transformer warns statically on discarded-value calls in branches.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
import warnings
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import core as jax_core
from jax import lax


# ---------------------------------------------------------------------------
# runtime converters (the `_jst` namespace injected into transformed code)
# ---------------------------------------------------------------------------

def _is_traced(x) -> bool:
    data = getattr(x, "_data", x)
    return isinstance(data, jax_core.Tracer)


def _pred_value(x):
    """Concrete bool of an eager predicate."""
    data = getattr(x, "_data", x)
    if hasattr(data, "item"):
        return bool(data.item())  # noqa: PTA006 -- eager control-flow predicate is concrete by contract
    return bool(data)


def _unwrap_vars(vs):
    from ..tensor.tensor import Tensor
    flags, raw = [], []
    for v in vs:
        if isinstance(v, Tensor):
            flags.append(True)
            raw.append(v._data)
        else:
            flags.append(False)
            raw.append(v)
    return flags, tuple(raw)


def _wrap_vars(flags, raw):
    from ..tensor.tensor import Tensor
    return tuple(Tensor._from_data(r) if f else r
                 for f, r in zip(flags, raw))


def _to_carry(raw):
    """Loop/branch carries must be arrays: lift numeric python scalars,
    reject unliftable types with a clear message."""
    out = []
    for r in raw:
        if isinstance(r, jax.Array) or hasattr(r, "aval"):
            out.append(r)
        elif isinstance(r, (bool, int, float, complex)):
            out.append(jnp.asarray(r))
        elif hasattr(r, "__array__"):
            out.append(jnp.asarray(r))
        else:
            raise TypeError(
                f"dy2static: variable of type {type(r).__name__} is assigned "
                "inside tensor-dependent control flow and cannot be carried "
                "through lax.cond/while_loop; hoist it out of the branch or "
                "keep the predicate a Python value")
    return tuple(out)


class _Undefined:
    """Sentinel for a variable not bound on (at least) one path through a
    converted branch (the reference's UndefinedVar): any USE raises with a
    clear message instead of a confusing NameError downstream."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<dy2static undefined>"

    def _raise(self, *a, **k):
        raise NameError(
            "dy2static: this variable is only assigned on one path of a "
            "tensor-dependent branch, so it has no defined value here; "
            "assign it on every path (or before the `if`) to use it after")

    __getattr__ = __call__ = __add__ = __radd__ = __sub__ = __mul__ = _raise
    __truediv__ = __getitem__ = __iter__ = __bool__ = __float__ = _raise


UNDEF = _Undefined()


def preval(name, local_ns):
    """Pre-branch value of `name`, or UNDEF if unbound (generated code)."""
    return local_ns.get(name, UNDEF)


def convert_ifelse(pred, true_fn, false_fn, vs, warn_calls=False):
    """vs: tuple of pre-values of the variables assigned in either branch.

    Concrete predicate: run one branch, plain Python. Traced predicate:
    run BOTH (pure) branches under the trace and jnp.where-select the
    outputs — select semantics, which is how XLA lowers small conditionals
    anyway, and which handles variables first bound inside the branches
    without the reference's undefined-var ceremony. A position left unbound
    by one branch becomes UNDEF (raises on use). Data-dependent trip counts
    (the case where avoiding both-paths execution actually matters) use
    real lax loops — see convert_while/convert_for_range."""
    from ..tensor.tensor import Tensor
    if not _is_traced(pred):
        return true_fn(*vs) if _pred_value(pred) else false_fn(*vs)
    if warn_calls:
        # deferred from transform time: only a *traced* predicate reaches
        # select semantics, so only then is the both-branches hazard real
        warnings.warn(
            "dy2static: an `if` branch contains a call whose result "
            "is discarded; under tracing BOTH branches execute "
            "(select semantics), so side effects run on both paths",
            stacklevel=2)
    t_out = true_fn(*vs)
    f_out = false_fn(*vs)
    pred_raw = getattr(pred, "_data", pred)
    if getattr(pred_raw, "ndim", 0) and pred_raw.size == 1:
        # reference `if` semantics need numel==1; squeezing keeps the
        # select from broadcasting the pred's [1] shape onto scalar
        # carries (e.g. the rewritten break/continue flags)
        pred_raw = pred_raw.reshape(())
    out = []
    for a, b in zip(t_out, f_out):
        if a is UNDEF or b is UNDEF:
            out.append(UNDEF)
            continue
        tensorish = isinstance(a, Tensor) or isinstance(b, Tensor)
        ar = getattr(a, "_data", a)
        br = getattr(b, "_data", b)
        sel = jnp.where(pred_raw, ar, br)
        out.append(Tensor._from_data(sel) if tensorish or _is_traced(sel)
                   else sel)
    return tuple(out)


def convert_while(cond_fn, body_fn, vs):
    # python path while the predicate stays concrete; a body can flip the
    # cond traced mid-loop (e.g. a rewritten break flag fed by a traced
    # value), in which case fall through to the lax path from the current
    # carry
    p = cond_fn(*vs)
    while not _is_traced(p):
        if not _pred_value(p):
            return vs
        vs = body_fn(*vs)
        p = cond_fn(*vs)
    if any(v is UNDEF for v in vs):
        raise ValueError(
            "dy2static: every variable assigned in a tensor-dependent while "
            "loop must be bound before the loop (the trip count may be zero)")
    flags, raw = _unwrap_vars(vs)

    def cond(carry):
        p = cond_fn(*_wrap_vars(flags, carry))
        raw = getattr(p, "_data", p)
        # while_loop needs a SCALAR bool; a size-1 pred (e.g. `x < 3` on a
        # [1]-shaped tensor) squeezes, anything larger errors in reshape
        return raw.reshape(()) if getattr(raw, "ndim", 0) else raw

    def body(carry):
        outs = body_fn(*_wrap_vars(flags, carry))
        _, raw_out = _unwrap_vars(outs)
        return _to_carry(raw_out)

    out = lax.while_loop(cond, body, _to_carry(raw))
    return _wrap_vars(flags, out)


def convert_for_range(bounds, body_fn, vs):
    """bounds: (start, stop, step) as written in `range(...)`. body_fn takes
    (i, *vars) and returns the updated vars."""
    from ..tensor.tensor import Tensor
    start, stop, step = bounds
    if not any(_is_traced(b) for b in bounds):
        s = [int(getattr(b, "_data", b)) if not isinstance(b, int) else b
             for b in bounds]
        for i in range(*s):
            vs = body_fn(i, *vs)
        return vs
    if isinstance(step, Tensor) or _is_traced(step):
        raise NotImplementedError(
            "dy2static: tensor-valued range() step is not supported; use a "
            "while loop")
    if any(v is UNDEF for v in vs):
        raise ValueError(
            "dy2static: every variable assigned in a tensor-bounded for loop "
            "must be bound before the loop (the trip count may be zero); "
            "initialize it before the `for`")
    flags, raw = _unwrap_vars(vs)
    lo = getattr(start, "_data", start)
    hi = getattr(stop, "_data", stop)
    if step not in (1, None):
        # fori_loop is unit-step; fold the step into the index
        n = (hi - lo + step - (1 if step > 0 else -1)) // step
        def body(t, carry):
            i = lo + t * step
            outs = body_fn(Tensor._from_data(jnp.asarray(i)),
                           *_wrap_vars(flags, carry))
            _, raw_out = _unwrap_vars(outs)
            return _to_carry(raw_out)
        out = lax.fori_loop(0, n, body, _to_carry(raw))
    else:
        def body(i, carry):
            outs = body_fn(Tensor._from_data(jnp.asarray(i)),
                           *_wrap_vars(flags, carry))
            _, raw_out = _unwrap_vars(outs)
            return _to_carry(raw_out)
        out = lax.fori_loop(lo, hi, body, _to_carry(raw))
    return _wrap_vars(flags, out)


def convert_bool(x):
    """`if t and u` style: bool() on a traced tensor must raise jax's usual
    error; on eager tensors return the python bool."""
    if _is_traced(x):
        return x  # let the caller (convert_ifelse) handle the tracer
    return x


class Dy2StaticUnsupportedError(RuntimeError):
    """A tensor-dependent control-flow escape dy2static cannot translate."""


def guard_pred(x, ctx):
    """Wrapped around predicates whose block contains an untranslatable
    break/continue/return: eager values pass through unchanged; a traced
    predicate raises a clear framework error instead of jax's raw
    concretization traceback (ref: dy2static raises its own error types)."""
    if _is_traced(x):
        raise Dy2StaticUnsupportedError(
            f"dy2static: tensor-dependent {ctx} cannot be translated to XLA "
            "control flow in this form. Translatable forms: `if <pred>: "
            "[assigns...]; break` / `continue` as direct statements of the "
            "loop body, and a trailing `if <pred>: return a` + `return b`. "
            "Otherwise restructure with an explicit flag variable, or keep "
            "the predicate a host value.")
    return x


def loop_pred(test, brk):
    """`while test` with a rewritten break: loop while test and not brk."""
    if _is_traced(test) or _is_traced(brk):
        from ..tensor.tensor import Tensor
        t = getattr(test, "_data", test)
        b = getattr(brk, "_data", brk)
        return Tensor._from_data(jnp.logical_and(t, jnp.logical_not(b)))
    return _pred_value(test) and not _pred_value(brk)


def not_escaped(*flags):
    """Gate for loop-body statements after a rewritten break/continue:
    true while no escape flag is set."""
    if any(_is_traced(f) for f in flags):
        from ..tensor.tensor import Tensor
        acc = None
        for f in flags:
            r = getattr(f, "_data", f)
            acc = r if acc is None else jnp.logical_or(acc, r)
        return Tensor._from_data(jnp.logical_not(acc))
    return not any(_pred_value(f) for f in flags)


def select_return(pred, a_fn, b_fn):
    """Trailing `if pred: return a` / `return b` pattern: eager runs one
    side; traced evaluates both (pure) and selects."""
    from ..tensor.tensor import Tensor
    if not _is_traced(pred):
        return a_fn() if _pred_value(pred) else b_fn()
    a, b = a_fn(), b_fn()
    pr = getattr(pred, "_data", pred)
    ar = getattr(a, "_data", a)
    br = getattr(b, "_data", b)
    sel = jnp.where(pr, ar, br)
    return (Tensor._from_data(sel)
            if isinstance(a, Tensor) or isinstance(b, Tensor)
            or _is_traced(sel) else sel)


# ---------------------------------------------------------------------------
# the AST pass
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names bound by assignments/augassigns/for-targets within a block
    (not descending into nested function/class defs)."""

    def __init__(self):
        self.names = []

    def _add(self, target):
        if isinstance(target, ast.Name):
            if target.id not in self.names:
                self.names.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs have their own scope

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_ClassDef(self, node):
        pass


def _assigned(stmts) -> list:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _contains_flow_escape(stmts) -> bool:
    """Escapes OUT of this block: break/continue not enclosed by a nested
    loop (those are local to that loop), or return anywhere (not in nested
    defs)."""
    class V(ast.NodeVisitor):
        def __init__(self):
            self.found = False
            self.loop_depth = 0

        def visit_Break(self, n):
            if self.loop_depth == 0:
                self.found = True

        def visit_Continue(self, n):
            if self.loop_depth == 0:
                self.found = True

        def visit_Return(self, n):
            self.found = True

        def visit_For(self, n):
            self.loop_depth += 1
            self.generic_visit(n)
            self.loop_depth -= 1

        def visit_While(self, n):
            self.loop_depth += 1
            self.generic_visit(n)
            self.loop_depth -= 1

        def visit_FunctionDef(self, n):
            pass

        def visit_AsyncFunctionDef(self, n):
            pass
    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _rewrite_escape_body(body, brk_name, cont_name):
    """The reference's break/continue flag rewriting (ref: dy2static
    BreakContinueTransformer), restricted to the structured form: every
    direct escape is `if <pred>: [stmts...]; break|continue` at loop-body
    top level (no orelse, no other escapes). The escape becomes a flag
    assignment and every subsequent statement is gated on the flags — the
    gated ifs then translate through convert_ifelse like any other.

    Gating rules: with any break present, EVERY statement gates on the
    persistent break flag — a rewritten `for` keeps looping after a break,
    so even statements textually before the escape must be skipped in
    later iterations (in the breaking iteration itself the flag is still
    unset when they run, preserving order). The continue flag resets each
    iteration and gates only statements AFTER its setting point.

    Returns (new_body, used_break, used_continue), or None when the body
    doesn't fit the structured form (caller falls back to guard_pred)."""
    def escape_kind(s):
        if not (isinstance(s, ast.If) and _contains_flow_escape([s])):
            return None
        if s.orelse or not s.body:
            return False
        last = s.body[-1]
        if not isinstance(last, (ast.Break, ast.Continue)) or \
                _contains_flow_escape(s.body[:-1]):
            return False
        return ast.Break if isinstance(last, ast.Break) else ast.Continue

    kinds = [escape_kind(s) for s in body]
    if any(k is False for k in kinds):
        return None
    if any(k is None and _contains_flow_escape([s])
           for k, s in zip(kinds, body)):
        return None  # bare break/continue, return, or non-if escape
    used_brk = any(k is ast.Break for k in kinds)
    used_cont = any(k is ast.Continue for k in kinds)

    out = []
    cont_seen = False
    for s, kind in zip(body, kinds):
        if kind is not None:
            flag = brk_name if kind is ast.Break else cont_name
            s = ast.If(test=s.test, body=s.body[:-1] + [
                ast.Assign(targets=[_name(flag, ast.Store())],
                           value=ast.Constant(value=True))], orelse=[])
        gates = ([brk_name] if used_brk else []) + \
                ([cont_name] if cont_seen else [])
        if gates:
            s = ast.If(test=_call_jst("not_escaped",
                                      [_name(f, ast.Load()) for f in gates]),
                       body=[s], orelse=[])
        out.append(s)
        if kind is ast.Continue:
            cont_seen = True
    return out, used_brk, used_cont


def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _make_fn(name, argnames, body, returns):
    """def name(a, b, ...): <body>; return (a', b', ...)"""
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    ret = ast.Return(value=ast.Tuple(
        elts=[_name(r, ast.Load()) for r in returns], ctx=ast.Load()))
    return ast.FunctionDef(name=name, args=args, body=body + [ret],
                           decorator_list=[], returns=None, type_params=[])


def _assign_tuple(names, value):
    if len(names) == 1:
        target = ast.Tuple(elts=[_name(names[0], ast.Store())],
                           ctx=ast.Store())
    else:
        target = ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                           ctx=ast.Store())
    return ast.Assign(targets=[target], value=value)


def _call_jst(fname, args):
    return ast.Call(
        func=ast.Attribute(value=_name("_jst", ast.Load()), attr=fname,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _prevals_tuple(names):
    """(_jst.preval('a', locals()), ...) — reads that tolerate names not yet
    bound (first bound inside the branch/loop body)."""
    return ast.Tuple(
        elts=[_call_jst("preval",
                        [ast.Constant(value=n),
                         ast.Call(func=_name("locals", ast.Load()),
                                  args=[], keywords=[])])
              for n in names], ctx=ast.Load())


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    # -- shared escape handling -------------------------------------------
    def _rewrite_loop_escapes(self, node):
        """Flag-rewrite structured break/continue in a loop body. Returns
        (init_stmts, used_break) and mutates node.body; on the
        unstructured form wraps nothing (caller guards) and returns None."""
        uid = self._uid()
        brk, cont = f"__dy2st_brk_{uid}", f"__dy2st_cont_{uid}"
        res = _rewrite_escape_body(node.body, brk, cont)
        if res is None:
            return None
        new_body, used_brk, used_cont = res
        inits = []
        if used_brk:
            inits.append(ast.Assign(targets=[_name(brk, ast.Store())],
                                    value=ast.Constant(value=False)))
        if used_cont:
            # reset each iteration: continue only skips the current pass
            new_body = [ast.Assign(targets=[_name(cont, ast.Store())],
                                   value=ast.Constant(value=False))] + new_body
            inits.append(ast.Assign(targets=[_name(cont, ast.Store())],
                                    value=ast.Constant(value=False)))
        node.body = new_body
        return inits, used_brk, brk

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _contains_flow_escape(node.body) or _contains_flow_escape(node.orelse):
            # python semantics preserved eagerly; under trace the guard
            # raises the framework's error instead of jax's concretization
            self.counter += 1
            node.test = _call_jst("guard_pred", [
                node.test,
                ast.Constant(value="`if` containing break/continue/return")])
            return node
        assigned = _assigned(node.body + node.orelse)
        if not assigned:
            # branch with no bindings (only side-effect calls): keep python
            # semantics; guard so a traced pred gets the framework error,
            # not jax's raw concretization traceback
            self.counter += 1
            node.test = _call_jst("guard_pred", [
                node.test,
                ast.Constant(value="`if` whose branches bind no variables "
                                   "(side effects only)")])
            return node
        # statement-level calls with discarded values (append/print/IO)
        # would run on BOTH paths under select semantics — but only a
        # traced predicate takes that path, so the warning is emitted
        # lazily from convert_ifelse, not at transform time
        has_discarded_call = any(
            isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            for stmt in node.body + node.orelse)
        uid = self._uid()
        tname, fname = f"__dy2st_true_{uid}", f"__dy2st_false_{uid}"
        true_fn = _make_fn(tname, assigned, node.body, assigned)
        false_fn = _make_fn(fname, assigned,
                            node.orelse or [ast.Pass()], assigned)
        call = _call_jst("convert_ifelse", [
            node.test,
            _name(tname, ast.Load()),
            _name(fname, ast.Load()),
            _prevals_tuple(assigned),
            ast.Constant(value=has_discarded_call),
        ])
        return [true_fn, false_fn, _assign_tuple(assigned, call)]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        inits = []
        if not node.orelse and _contains_flow_escape(node.body):
            rewritten = self._rewrite_loop_escapes(node)
            if rewritten is not None:
                inits, used_brk, brk = rewritten
                if used_brk:
                    node.test = _call_jst("loop_pred",
                                          [node.test,
                                           _name(brk, ast.Load())])
        self.generic_visit(node)
        if node.orelse or _contains_flow_escape(node.body):
            self.counter += 1
            node.test = _call_jst("guard_pred", [
                node.test,
                ast.Constant(value="while loop with break/continue/return "
                                   "in an untranslatable position")])
            return node
        loop_vars = _assigned(node.body)  # cond reads non-assigned names
        if not loop_vars:                 # via closure; only stores carry
            return node
        uid = self._uid()
        cname, bname = f"__dy2st_cond_{uid}", f"__dy2st_body_{uid}"
        cond_fn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=a) for a in loop_vars],
                               vararg=None, kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        body_fn = _make_fn(bname, loop_vars, node.body, loop_vars)
        call = _call_jst("convert_while", [
            _name(cname, ast.Load()),
            _name(bname, ast.Load()),
            _prevals_tuple(loop_vars),
        ])
        return inits + [cond_fn, body_fn, _assign_tuple(loop_vars, call)]

    # -- for i in range(...) ----------------------------------------------
    def visit_For(self, node):
        is_range = (not node.orelse
                    and isinstance(node.target, ast.Name)
                    and isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords)
        inits, post = [], []
        if is_range and _contains_flow_escape(node.body):
            # flag-rewrite: the fori_loop runs the full trip count and the
            # gated body is a no-op after a break (the carry is unchanged).
            # ONLY for range() loops — a non-range loop keeps its python
            # break, which a rewrite would silently remove.
            #
            # The loop variable stays readable after the loop: a capture
            # (inserted BEFORE the rewrite so it gets break-gated) carries
            # the index of the last non-broken iteration, and the target
            # is rebound from it after the loop.
            tgt = node.target.id
            ivis = f"__dy2st_ivis_{self.counter + 1}"
            node.body = [ast.Assign(targets=[_name(ivis, ast.Store())],
                                    value=_name(tgt, ast.Load()))] + node.body
            rewritten = self._rewrite_loop_escapes(node)
            if rewritten is not None:
                inits, _, _ = rewritten
                # pre-bind for the zero-trip case (python would leave the
                # target unbound; we bind it to the range start)
                start = (node.iter.args[0] if len(node.iter.args) >= 2
                         else ast.Constant(value=0))
                import copy as _copy
                inits.append(ast.Assign(targets=[_name(ivis, ast.Store())],
                                        value=_copy.deepcopy(start)))
                post = [ast.Assign(targets=[_name(tgt, ast.Store())],
                                   value=_name(ivis, ast.Load()))]
            else:
                node.body = node.body[1:]  # undo the capture
        self.generic_visit(node)
        if not is_range or _contains_flow_escape(node.body):
            if is_range and _contains_flow_escape(node.body):
                # traced range() bounds would raise jax's raw conversion
                # error; guard each bound for the framework error instead
                self.counter += 1
                node.iter.args = [
                    _call_jst("guard_pred", [
                        a, ast.Constant(value="for-range bound in a loop "
                                              "with an untranslatable "
                                              "break/continue/return")])
                    for a in node.iter.args]
            return node
        assigned = [n for n in _assigned(node.body) if n != node.target.id]
        if not assigned:
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            bounds = [ast.Constant(value=0), rargs[0], ast.Constant(value=1)]
        elif len(rargs) == 2:
            bounds = [rargs[0], rargs[1], ast.Constant(value=1)]
        else:
            bounds = list(rargs)
        uid = self._uid()
        bname = f"__dy2st_forbody_{uid}"
        body_fn = _make_fn(bname, [node.target.id] + assigned, node.body,
                           assigned)
        call = _call_jst("convert_for_range", [
            ast.Tuple(elts=bounds, ctx=ast.Load()),
            _name(bname, ast.Load()),
            _prevals_tuple(assigned),
        ])
        return inits + [body_fn, _assign_tuple(assigned, call)] + post

    # -- trailing `if p: return a` / `return b` ----------------------------
    def visit_FunctionDef(self, node):
        new_body = []
        i = 0
        while i < len(node.body):
            s = node.body[i]
            nxt = node.body[i + 1] if i + 1 < len(node.body) else None
            if (isinstance(s, ast.If) and len(s.body) == 1
                    and isinstance(s.body[0], ast.Return)
                    and s.body[0].value is not None):
                a_val = s.body[0].value
                b_val = None
                consumed = 1
                if (len(s.orelse) == 1 and isinstance(s.orelse[0], ast.Return)
                        and s.orelse[0].value is not None):
                    b_val = s.orelse[0].value
                elif (not s.orelse and isinstance(nxt, ast.Return)
                      and nxt.value is not None):
                    b_val = nxt.value
                    consumed = 2
                if b_val is not None:
                    self.counter += 1
                    lam = lambda v: ast.Lambda(
                        args=ast.arguments(posonlyargs=[], args=[],
                                           vararg=None, kwonlyargs=[],
                                           kw_defaults=[], kwarg=None,
                                           defaults=[]),
                        body=v)
                    new_body.append(ast.Return(value=_call_jst(
                        "select_return", [s.test, lam(a_val), lam(b_val)])))
                    i += consumed
                    continue
            new_body.append(s)
            i += 1
        node.body = new_body
        self.generic_visit(node)
        return node


# ---------------------------------------------------------------------------
# entry: transform a function's source
# ---------------------------------------------------------------------------

_JST_NS = types.SimpleNamespace(
    convert_ifelse=convert_ifelse,
    convert_while=convert_while,
    convert_for_range=convert_for_range,
    convert_bool=convert_bool,
    preval=preval,
    guard_pred=guard_pred,
    loop_pred=loop_pred,
    not_escaped=not_escaped,
    select_return=select_return,
)


_STRIP_DECORATORS = ("to_static", "jit.to_static", "paddle.jit.to_static",
                     "dy2static", "convert_control_flow")


def _should_strip(dec) -> bool:
    # call-form decorators (@to_static(input_spec=...)) match on their func
    if isinstance(dec, ast.Call):
        dec = dec.func
    expr = ast.unparse(dec) if hasattr(ast, "unparse") else ""
    return any(expr.endswith(s) for s in _STRIP_DECORATORS)


@functools.lru_cache(maxsize=256)
def _transform_cached(fn):
    return _transform(fn)


def _transform(fn: Callable) -> Callable:
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn  # no source (C ext, REPL lambda): fall back to trace-only
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = [d for d in fdef.decorator_list
                           if not _should_strip(d)]
    transformer = _Dy2StaticTransformer()
    new_tree = transformer.visit(tree)
    if transformer.counter == 0:
        return fn  # nothing to convert
    ast.fix_missing_locations(new_tree)
    ns = dict(fn.__globals__)
    # closures: snapshot cell contents into the namespace (read-only view)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                ns[name] = cell.cell_contents
            except ValueError:
                pass
    ns["_jst"] = _JST_NS
    code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                   mode="exec")
    exec(code, ns)
    new_fn = ns[fdef.name]
    new_fn.__wrapped__ = fn
    return new_fn


def convert_to_static(fn: Callable) -> Callable:
    """Public: AST-translate tensor control flow in `fn`. Falls back to the
    original function when source is unavailable or nothing needs converting
    (the reference's fallback-to-eager contract)."""
    try:
        return _transform_cached(fn)
    except TypeError:  # unhashable callables
        return _transform(fn)
    except Exception as e:  # transform bug: never break the user's function
        warnings.warn(f"dy2static: falling back to trace-only for "
                      f"{getattr(fn, '__name__', fn)}: {e}")
        return fn
