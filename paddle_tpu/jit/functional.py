"""Functional tracing of Layers (the to_static substrate).

Ref: python/paddle/jit/ (dy2static + SOT). The reference translates Python
AST/bytecode to ProgramDesc. TPU-native: a Layer's forward is ALREADY jax-
traceable — Tensors wrap tracers transparently — so "to static" is just:
swap parameter/buffer arrays for tracer arrays, run forward under no_grad
(the tape is unnecessary inside a compiled graph; jax.grad differentiates the
traced function), collect buffer mutations (BatchNorm running stats) as
explicit outputs, and jax.jit the result.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..autograd import engine
from ..framework import random as random_mod
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


def state_arrays(layer: Layer):
    """(params, buffers): name -> jax array."""
    params = {k: p._data for k, p in layer.named_parameters()}
    buffers = {k: b._data for k, b in layer.named_buffers() if b is not None}
    return params, buffers


def functional_call(layer: Layer, params: Dict[str, Any], args, kwargs=None,
                    buffers: Dict[str, Any] = None, rng_key=None,
                    training: bool = None):
    """Run layer.forward with the given arrays bound as parameters/buffers.

    Returns (outputs, new_buffers) where outputs have Tensors replaced by raw
    arrays. Safe under jax tracing (params may be tracers).
    """
    kwargs = kwargs or {}
    param_objs = dict(layer.named_parameters())
    buffer_objs = {k: b for k, b in layer.named_buffers() if b is not None}
    saved_p = {k: p._data for k, p in param_objs.items()}
    saved_b = {k: b._data for k, b in buffer_objs.items()}
    saved_train = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    for k, v in params.items():
        if k in param_objs:
            param_objs[k]._data = v
    if buffers:
        for k, v in buffers.items():
            if k in buffer_objs:
                buffer_objs[k]._data = v

    def run():
        t_args = [Tensor._from_data(a) if _is_array(a) else a for a in args]
        t_kwargs = {k: Tensor._from_data(v) if _is_array(v) else v
                    for k, v in kwargs.items()}
        with engine.no_grad():
            out = layer(*t_args, **t_kwargs)
        return out

    try:
        if rng_key is not None:
            with random_mod.trace_rng(rng_key):
                out = run()
        else:
            out = run()
        new_buffers = {k: b._data for k, b in buffer_objs.items()}
    finally:
        for k, p in param_objs.items():
            p._data = saved_p[k]
        for k, b in buffer_objs.items():
            b._data = saved_b[k]
        layer.training = saved_train
        for sub in layer.sublayers():
            sub.training = saved_train
    out_arrays = jax.tree_util.tree_map(
        lambda x: x._data if isinstance(x, Tensor) else x, out,
        is_leaf=lambda x: isinstance(x, Tensor))
    return out_arrays, new_buffers


def _is_array(a):
    return isinstance(a, jax.Array) or hasattr(a, "aval")


# host-value types whose change must invalidate a cached trace (the SOT
# tier's guard property: a python flag baked into a trace at trace time
# silently replays stale without a recheck)
_GUARD_TYPES = (int, float, bool, str, bytes, type(None))


def _layer_host_guard(layer: Layer):
    """Snapshot of the layer tree's plain-python attribute values (the
    host values a trace captures as constants). Compared per call; a
    mismatch forces a retrace — the reference's SOT guards, at attribute
    granularity."""
    snap = []
    stack = [("", layer)]
    while stack:
        path, sub = stack.pop()
        for k, v in vars(sub).items():
            if k.startswith("_") or k == "training":
                continue
            if isinstance(v, _GUARD_TYPES):
                snap.append((path, k, v))
            elif isinstance(v, (tuple, list)) and \
                    all(isinstance(e, _GUARD_TYPES) for e in v):
                snap.append((path, k, tuple(v)))
        for name, child in getattr(sub, "_sub_layers", {}).items():
            stack.append((f"{path}.{name}", child))
    return tuple(sorted(snap))


def _fn_host_guard(fn):
    """Snapshot of a function's captured host values: closure cells and
    module globals it names. Plain-python values enter the guard key;
    functions/modules/types are treated as stable; ANY other captured
    value (list, dict, array, object) makes the function UNCACHEABLE
    (returns None) — a mutable capture can change without changing
    identity, and a stale replay is worse than a rebuild."""
    import types as _t
    stable = (_t.FunctionType, _t.BuiltinFunctionType, _t.ModuleType, type)
    snap = []
    code = fn.__code__

    def visit(name, v, kind):
        if isinstance(v, _GUARD_TYPES):
            snap.append((kind, name, v))
            return True
        if isinstance(v, (tuple, list)) and \
                all(isinstance(e, _GUARD_TYPES) for e in v):
            snap.append((kind, name, tuple(v)))
            return True
        return isinstance(v, stable)

    for name, cell in zip(code.co_freevars, fn.__closure__ or ()):
        try:
            v = cell.cell_contents
        except ValueError:  # pragma: no cover - unfilled cell
            continue
        if not visit(name, v, "cell"):
            return None
    g = fn.__globals__
    for name in code.co_names:
        if name in g and not visit(name, g[name], "global"):
            return None
    return tuple(snap)


class TracedLayer:
    """jit-compiled callable over a Layer (paddle.jit.to_static on a Layer).

    The compiled trace bakes in the layer's python attribute values
    (dropout rates, flags, sizes); those are re-checked on every call via
    _layer_host_guard and a change triggers a retrace instead of silently
    replaying the stale program."""

    def __init__(self, layer: Layer, training=False):
        self.layer = layer
        self.training = training
        self._guard = None
        self._fn = None

    def _build(self):
        layer, training = self.layer, self.training

        @functools.partial(jax.jit, static_argnums=())
        def _fn(params, buffers, arg_arrays):
            out, new_buf = functional_call(layer, params, arg_arrays,
                                           buffers=buffers,
                                           training=training)
            return out, new_buf

        return _fn

    def __call__(self, *args):
        guard = _layer_host_guard(self.layer)
        if self._fn is None or guard != self._guard:
            self._fn = self._build()
            self._guard = guard
        params, buffers = state_arrays(self.layer)
        arg_arrays = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                           for a in args)
        out, new_buf = self._fn(params, buffers, arg_arrays)
        # propagate buffer updates (running stats) back to the layer
        for k, b in self.layer.named_buffers():
            if b is not None and k in new_buf:
                b._data = new_buf[k]
        return jax.tree_util.tree_map(
            lambda x: Tensor._from_data(x) if _is_array(x) else x, out)


def to_static(function=None, input_spec=None, build_strategy=None,
              full_graph=True, backend=None):
    """paddle.jit.to_static parity: Layer -> TracedLayer; function -> jitted.

    Function forwards run through the dy2static AST pass first (jit/
    dy2static.py): `if`/`while`/`for range` over tensor values become
    lax.cond / while_loop / fori_loop under tracing, plain Python eagerly."""
    def decorate(obj):
        if isinstance(obj, Layer):
            return TracedLayer(obj)

        from .dy2static import convert_to_static
        converted = convert_to_static(obj)
        # trace cache keyed by the function's captured host values (and
        # the call kwargs): a changed closure/global retraces instead of
        # replaying stale; unchanged values REUSE the compiled program
        # (previously every call built a fresh jax.jit and recompiled)
        jit_cache = {}

        @functools.wraps(obj)
        def wrapper(*args, **kwargs):
            arrs = tuple(a._data if isinstance(a, Tensor) else a for a in args)
            guard = _fn_host_guard(obj)
            if guard is None:  # mutable capture: never cache (see guard)
                key = None
            else:
                try:
                    key = (guard, tuple(sorted(kwargs.items())))
                    hash(key)  # sorted() doesn't hash values; probe now
                except TypeError:  # unhashable/unorderable kwarg
                    key = None

            def build():
                def fn(arg_arrays):
                    t_args = [Tensor._from_data(a) if _is_array(a) else a
                              for a in arg_arrays]
                    with engine.no_grad():
                        out = converted(*t_args, **kwargs)
                    return jax.tree_util.tree_map(
                        lambda x: x._data if isinstance(x, Tensor) else x, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                return jax.jit(fn)

            if key is None:
                jitted = build()
            else:
                jitted = jit_cache.get(key)
                if jitted is None:
                    if len(jit_cache) >= 32:
                        # a per-call-changing captured value (step counter,
                        # annealed float) would otherwise grow this without
                        # bound; evict oldest (dict preserves insert order)
                        jit_cache.pop(next(iter(jit_cache)))
                    jitted = jit_cache[key] = build()
            out = jitted(arrs)
            return jax.tree_util.tree_map(
                lambda x: Tensor._from_data(x) if _is_array(x) else x, out)
        return wrapper

    if function is not None:
        return decorate(function)
    return decorate
