"""jit.save / jit.load (ref: python/paddle/jit/api.py save/load,
 python/paddle/jit/translated_layer.py).

The reference saves a translated ProgramDesc + params; loading yields a
TranslatedLayer runnable without the original Python class.  TPU-native: the
Layer's functional forward is exported to **StableHLO** with ``jax.export``
(parameters baked in as constants for inference) and serialized; params are
additionally saved as numpy for state_dict-style reload. A loaded model is a
``TranslatedLayer`` whose __call__ runs the deserialized XLA computation —
no original source needed, and the artifact is loadable from C++ via the
StableHLO bytes in <path>.pdmodel.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import jax
import jax.export  # jax>=0.4.34 no longer re-exports it as a jax attribute
import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from .functional import functional_call, state_arrays


def _resolve_specs(layer_or_fn, input_spec) -> List[jax.ShapeDtypeStruct]:
    from ..static import InputSpec
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            shape = [1 if (d is None or d < 0) else int(d) for d in s.shape]
            specs.append(jax.ShapeDtypeStruct(tuple(shape),
                                              jnp.dtype(str(s.dtype))))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s._data.shape),
                                              s._data.dtype))
        else:
            a = jnp.asarray(np.asarray(s))  # noqa: PTA006 -- example inputs are host data; spec build is pre-trace
            specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    return specs


def save(layer, path: str, input_spec: Optional[Sequence] = None, **config):
    """Export ``layer`` (or a TracedLayer) for deployment.

    Produces: <path>.pdmodel (serialized StableHLO, params baked),
    <path>.pdiparams.npz (raw params), <path>.json (meta).
    """
    from .functional import TracedLayer
    if isinstance(layer, TracedLayer):
        layer = layer.layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer or TracedLayer")
    if input_spec is None:
        raise ValueError("jit.save requires input_spec (shapes to export at)")

    params, buffers = state_arrays(layer)
    specs = _resolve_specs(layer, input_spec)

    def fwd(*arg_arrays):
        out, _ = functional_call(layer, params, arg_arrays, buffers=buffers,
                                 training=False)
        return out

    # Export for both platforms so an artifact saved during CPU development
    # deploys to TPU and vice versa.
    exported = jax.export.export(jax.jit(fwd),
                                 platforms=("cpu", "tpu"))(*specs)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    np.savez(path + ".pdiparams.npz",
             **{k: np.asarray(v) for k, v in params.items()})  # noqa: PTA006 -- checkpoint save is host I/O by contract
    with open(path + ".json", "w") as f:
        json.dump({
            "format": "stablehlo-exported",
            "num_inputs": len(specs),
            "input_shapes": [list(s.shape) for s in specs],
            "input_dtypes": [str(s.dtype) for s in specs],
            "param_names": sorted(params.keys()),
        }, f)


class TranslatedLayer:
    """Runnable loaded model (ref: TranslatedLayer). Callable like a Layer;
    params are frozen into the compiled computation."""

    def __init__(self, exported, meta, params):
        self._exported = exported
        self.meta = meta
        self._params = params  # dict name -> np array (inspection/export)

    def __call__(self, *args):
        arrays = [a._data if isinstance(a, Tensor)
                  else jnp.asarray(np.asarray(a)) for a in args]  # noqa: PTA006 -- loaded-program boundary stages host inputs once
        out = self._exported.call(*arrays)
        return jax.tree_util.tree_map(
            lambda x: Tensor._from_data(x, stop_gradient=True), out)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a jit-loaded inference artifact cannot be "
                           "switched to training mode; params are baked into "
                           "the compiled graph")

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._params.items()}


def load(path: str) -> TranslatedLayer:
    with open(path + ".json") as f:
        meta = json.load(f)
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    params = {}
    if os.path.exists(path + ".pdiparams.npz"):
        loaded = np.load(path + ".pdiparams.npz")
        params = {k: loaded[k] for k in loaded.files}
    return TranslatedLayer(exported, meta, params)
