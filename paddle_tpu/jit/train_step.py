"""Compiled SPMD training step (the performance path).

Ref: the reference's fleet static-graph path (SURVEY.md §3.5) — one compiled
program per step. Here: jax.value_and_grad over the Layer's functional form +
the optimizer's pure update rule, jitted once with donated state. When a mesh
+ sharding specs are given, parameters/optimizer states are placed with
NamedShardings (TP from param.pspec, ZeRO from group_sharded), the batch is
dp-sharded, and XLA emits all collectives.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import envs
from .. import observability
from ..distributed import sharding_utils
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from .functional import functional_call, state_arrays


class TrainStep:
    """Owns the (possibly sharded) param/opt-state arrays; callable per batch.

    train_step = TrainStep(model, loss_fn, optimizer, mesh=hcg.mesh,
                           batch_spec=P('dp'))
    loss = train_step(x, y)
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 mesh: Optional[Mesh] = None, batch_spec=None,
                 grad_accum: int = 1, donate: bool = True, rng_seed: int = 0,
                 grad_sync: Optional[str] = None,
                 grad_bucket_mb: Optional[float] = None,
                 param_prefetch: Optional[bool] = None,
                 param_bucket_mb: Optional[float] = None,
                 telemetry: Optional[bool] = None,
                 telemetry_dir: Optional[str] = None,
                 tokens_per_step: Optional[int] = None,
                 flight_recorder: Optional[bool] = None,
                 fleet=None, ledger=None, checkpoint=None):
        # rolling-checkpoint + preemption orchestration (PR 13): a
        # CheckpointManager instance or a root directory string. on_step
        # fires after every completed step; interval pacing and the
        # SIGTERM path live in the manager.
        if isinstance(checkpoint, str):
            from ..distributed.checkpoint.manager import CheckpointManager
            checkpoint = CheckpointManager(checkpoint)
        self.checkpoint = checkpoint
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.grad_accum = int(grad_accum)
        self._step_count = 0
        self._rng = jax.random.PRNGKey(rng_seed)

        params, buffers = state_arrays(model)
        self.param_objs = dict(model.named_parameters())
        trainable = {k: p for k, p in self.param_objs.items()
                     if not p.stop_gradient}
        self.trainable_keys = list(trainable)

        opt_states = {}
        for k in self.trainable_keys:
            opt_states[k] = optimizer._create_accumulators(self.param_objs[k])
        self.wd_map = {k: optimizer._weight_decay for k in self.trainable_keys}

        if mesh is not None:
            from ..distributed.fleet.meta_parallel.sharding.group_sharded \
                import mesh_resolved_spec
            # ZeRO specs attached by group_sharded_parallel are re-derived
            # here against the REAL mesh degree (divisibility enforced —
            # see mesh_resolved_spec); non-ZeRO pspecs pass through.
            gs_specs = {k: mesh_resolved_spec(p, mesh)
                        for k, p in self.param_objs.items()
                        if getattr(p, "opt_state_pspec", None) is not None}
            self.param_shardings = {}
            for k, p in self.param_objs.items():
                if getattr(p, "sharding_level", None) == "p_g_os" \
                        and gs_specs.get(k) is not None:
                    self.param_shardings[k] = NamedSharding(mesh, gs_specs[k])
                else:
                    self.param_shardings[k] = \
                        sharding_utils.param_sharding(p, mesh)
            params = {k: jax.device_put(v, self.param_shardings[k])
                      for k, v in params.items()}
            # ZeRO stage 1/2 (group_sharded 'os'/'os_g'): optimizer states
            # shard over the 'sharding' axis even when the param itself is
            # replicated — XLA then reduce-scatters grads into the update.
            opt_shardings = {}
            for k in self.trainable_keys:
                os_spec = gs_specs.get(k)
                opt_shardings[k] = (NamedSharding(mesh, os_spec)
                                    if os_spec is not None
                                    else self.param_shardings[k])
            opt_states = {
                k: jax.tree_util.tree_map(
                    lambda a, s=opt_shardings[k], nd=params[k].ndim:
                        jax.device_put(a, s if a.ndim == nd else
                                       NamedSharding(mesh, P())),
                    opt_states[k])
                for k in self.trainable_keys}
            buffers = {k: jax.device_put(v, NamedSharding(mesh, P()))
                       for k, v in buffers.items()}
            # the stage-1 vs stage-2 distinction (ZeRO): stage 1 keeps grads
            # replicated (one all-reduce, update gathers from sharded
            # states); stage 2/3 constrain grads onto the sharding axis, so
            # XLA lowers the grad sum to a reduce-scatter (half the grad
            # traffic — the reference's stage-2 win) and each rank updates
            # only its shard
            self.grad_shardings = {}
            for k in self.trainable_keys:
                p = self.param_objs[k]
                lvl = getattr(p, "sharding_level", None)
                os_spec = gs_specs.get(k)
                if lvl in ("os_g", "p_g_os") and os_spec is not None:
                    self.grad_shardings[k] = NamedSharding(mesh, os_spec)
                elif lvl == "os":
                    self.grad_shardings[k] = NamedSharding(mesh, P())
        else:
            self.grad_shardings = {}
        self.params = params
        self.buffers = buffers
        self.opt_states = opt_states

        param_shardings_ref = getattr(self, "param_shardings", None)
        grad_shardings_ref = self.grad_shardings
        clip = optimizer._grad_clip
        clip_norm = getattr(clip, "clip_norm", None) if clip is not None else None
        update_rule = optimizer._update
        wd_map = dict(self.wd_map)
        trainable_keys = list(self.trainable_keys)
        model_ref = model
        loss_ref = loss_fn
        mesh_ref = mesh
        bspec = batch_spec

        def compute_loss(train_params, frozen_params, buffers, batch, rng,
                         use_hints=True):
            all_params = {**frozen_params, **train_params}
            def run():
                out, new_buf = functional_call(model_ref, all_params,
                                               batch["inputs"], buffers=buffers,
                                               rng_key=rng, training=True)
                t_out = Tensor._from_data(out) if not isinstance(out, tuple) \
                    else tuple(Tensor._from_data(o) for o in out)
                labels = [Tensor._from_data(l) for l in batch["labels"]]
                loss = loss_ref(t_out, *labels)
                return loss._data.astype(jnp.float32), new_buf
            # hints are skipped inside the explicit-sync shard_map island:
            # with_sharding_constraint is meaningless on manual (per-shard)
            # values, and the island only activates when mp/pp/sep are trivial
            if mesh_ref is not None and use_hints:
                with _mesh_hints(mesh_ref):
                    return run()
            return run()

        accum = int(grad_accum)

        def accum_loss_grads(train_params, frozen_params, buffers, batch,
                             rng, use_hints=True):
            compute = functools.partial(compute_loss, use_hints=use_hints)
            """Gradient merge (ref: GradientMergeOptimizer / pipeline
            accumulate_steps): split the batch into `accum` microbatches on
            axis 0 and lax.scan them, summing grads in the carry (O(1) grad
            memory) and applying ONE optimizer update for the mean."""
            if accum <= 1:
                return jax.value_and_grad(compute, has_aux=True)(
                    train_params, frozen_params, buffers, batch, rng)

            def split(a):
                if a.ndim == 0 or a.shape[0] % accum:
                    raise ValueError(
                        f"grad_accum={accum} must divide batch dim "
                        f"{a.shape[:1]}")
                # STRIDED split (row i of microbatch m is global row
                # m + i*accum): under a dp-sharded batch each microbatch
                # keeps rows on every dp shard; a contiguous split would
                # park whole microbatches on one shard and force XLA to
                # reshard every scan step
                a = a.reshape((a.shape[0] // accum, accum) + a.shape[1:])
                return jnp.swapaxes(a, 0, 1)

            mb = jax.tree_util.tree_map(split, batch)
            rngs = jax.random.split(rng, accum)
            g0 = jax.tree_util.tree_map(jnp.zeros_like, train_params)

            def body(carry, xs):
                bufs, gsum, lsum = carry
                batch_i, rng_i = xs
                (l, new_bufs), g = jax.value_and_grad(
                    compute, has_aux=True)(train_params, frozen_params,
                                           bufs, batch_i, rng_i)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
                return (new_bufs, gsum, lsum + l), None

            (new_buffers, gsum, lsum), _ = jax.lax.scan(
                body, (buffers, g0, jnp.zeros((), jnp.float32)), (mb, rngs))
            grads = jax.tree_util.tree_map(lambda g: g / accum, gsum)
            return (lsum / accum, new_buffers), grads

        # --- explicit bucketed/per-param gradient sync (DataParallel /
        # GroupSharded stage-1/2). Instead of GSPMD's implicit per-parameter
        # grad reduces, a fully-manual shard_map island computes per-shard
        # grads and issues the reduces itself — one fused psum per size-capped
        # bucket, in reverse parameter order, so each bucket's collective
        # overlaps the rest of backward. Opt-in (grad_sync=/env); only
        # activates when every non-trivial mesh axis is a data axis (dp/
        # sharding) — hybrid mp/pp/sep keeps the GSPMD path.
        sync_mode = grad_sync or envs.get("PADDLE_TPU_GRAD_SYNC")
        reduce_axes = ()
        if sync_mode not in ("auto", "explicit", "bucketed"):
            raise ValueError(f"grad_sync must be auto/explicit/bucketed, "
                             f"got {sync_mode!r}")
        if sync_mode != "auto":
            if mesh is None or batch_spec is None:
                sync_mode = "auto"
            else:
                nontrivial = {ax for ax, sz in mesh.shape.items() if sz > 1}
                reduce_axes = tuple(ax for ax in ("dp", "sharding")
                                    if mesh.shape.get(ax, 1) > 1)
                if not reduce_axes or nontrivial - {"dp", "sharding"}:
                    sync_mode, reduce_axes = "auto", ()
        self.grad_sync_mode = sync_mode
        self.grad_buckets = None
        if sync_mode == "bucketed":
            if grad_bucket_mb is None:
                grad_bucket_mb = getattr(model, "_comm_buffer_mb", None)
            if grad_bucket_mb is None:
                grad_bucket_mb = envs.get("PADDLE_TPU_DP_BUCKET_MB")
            shapes = {k: (tuple(params[k].shape), params[k].dtype.itemsize)
                      for k in trainable_keys}
            self.grad_buckets = sharding_utils.plan_grad_buckets(
                shapes, int(float(grad_bucket_mb) * 2 ** 20))
        buckets_ref = self.grad_buckets
        sync_axes = reduce_axes

        # --- stage-3 (ZeRO-3) param-gather prefetch: bucket the sharded
        # params in FORWARD order (same planner as the grad buckets, not
        # reversed) and issue each bucket's all-gather one bucket ahead of
        # first use inside the compiled step (sharding_utils.
        # prefetch_param_gathers). Default follows the overlap switch
        # (PADDLE_TPU_TP_OVERLAP) like the ring matmuls; pure data movement,
        # loss is bit-identical to the non-prefetched stage 3.
        self.param_gather_buckets = None
        prefetch_shardings = {}
        pf_shapes = {}
        if mesh is not None and mesh.shape.get("sharding", 1) > 1:
            if param_prefetch is None:
                from ..parallel import collective_matmul as _cm
                param_prefetch = _cm.overlap_enabled()
            if param_prefetch:
                for k in trainable_keys:
                    p = self.param_objs[k]
                    if getattr(p, "sharding_level", None) != "p_g_os":
                        continue
                    full = getattr(p, "_pre_gs_pspec", None) or P()
                    if self.param_shardings[k].spec == full:
                        continue  # indivisible shape: never actually sharded
                    pf_shapes[k] = (tuple(params[k].shape),
                                    params[k].dtype.itemsize)
                    prefetch_shardings[k] = NamedSharding(mesh, full)
                if pf_shapes:
                    cap = (int(float(param_bucket_mb) * 2 ** 20)
                           if param_bucket_mb is not None
                           else int(getattr(model, "_gs_buffer_bytes",
                                            2 ** 23)))
                    self.param_gather_buckets = \
                        sharding_utils.plan_grad_buckets(
                            pf_shapes, cap, reverse=False)
        pf_buckets_ref = self.param_gather_buckets
        pf_shardings_ref = prefetch_shardings

        # --- step telemetry (observability.StepMetrics). Explicit arg wins,
        # else PADDLE_TPU_TELEMETRY. Nothing below adds host syncs: wall
        # times are perf_counter intervals around the ASYNC dispatch, FLOPs
        # are captured once per compile from the lowered program's cost
        # analysis, memory stats are host-side PJRT queries.
        self.telemetry = None
        self._flops_stale = True
        self._seen_cache_size = 0
        # failure flight recorder (observability.FlightRecorder): rings the
        # last N dispatch records host-side and dumps them to
        # PADDLE_TPU_TELEMETRY_DIR when a step raises or its wall time
        # spikes. Independent of the telemetry switch so post-mortems don't
        # depend on having had telemetry on.
        self.recorder = (
            observability.FlightRecorder(source="train_step")
            if observability.flight_recorder_enabled(flight_recorder)
            else None)
        if observability.telemetry_enabled(telemetry):
            self.telemetry = observability.StepMetrics(
                name="train_step", tokens_per_step=tokens_per_step,
                n_devices=(mesh.size if mesh is not None else 1))
            logdir = telemetry_dir or observability.telemetry_dir()
            if logdir:
                rank = observability.process_rank()
                self.telemetry.attach(observability.JsonlWriter(
                    os.path.join(logdir, f"steps_rank{rank:03d}.jsonl")))
            observability.set_active(self.telemetry)
            observability.set_counter(
                "grad_sync.mode." + sync_mode, 1)
        # fleet monitor (PR 15): cross-rank step/comm/memory aggregation,
        # one host-side allgather per reporting interval, nothing on the
        # step hot path. Accepts a shared FleetMonitor instance (the
        # multichip dryrun's), True/False, or None -> PADDLE_TPU_FLEET.
        if isinstance(fleet, observability.FleetMonitor):
            self.fleet = fleet
        elif observability.fleet_enabled(fleet if isinstance(fleet, bool)
                                         else None):
            logdir = telemetry_dir or observability.telemetry_dir()
            self.fleet = observability.FleetMonitor(
                recorder=self.recorder,
                out_path=(os.path.join(logdir, "fleet_health.jsonl")
                          if logdir else None))
        else:
            self.fleet = None
        # roofline ledger (PR 17): itemizes step time into named kernel
        # component lines from the cost_estimate FLOPs/bytes captured while
        # tracing. Accepts a shared RooflineLedger instance, True/False, or
        # None -> PADDLE_TPU_LEDGER. Measurement-only: the compiled program
        # is untouched, the only hot-path cost is one perf_counter read.
        if isinstance(ledger, observability.RooflineLedger):
            self.ledger = ledger
        elif observability.ledger_enabled(ledger if isinstance(ledger, bool)
                                          else None):
            self.ledger = observability.RooflineLedger(name="train_step")
        else:
            self.ledger = None
        if self.fleet is not None and self.telemetry is not None:
            try:
                self.telemetry.register_into(self.fleet.registry)
            except ValueError:
                pass  # shared monitor: an earlier TrainStep registered
        if self.grad_buckets is not None:
            sizes = sharding_utils.bucket_bytes(shapes, self.grad_buckets)
            observability.set_counter("grad_sync.n_buckets",
                                      len(self.grad_buckets))
            observability.set_counter("grad_sync.total_bytes", sum(sizes))
            for i, nbytes in enumerate(sizes):
                # .plan_bytes: the static bucket payload; the traced span
                # separately tallies .bytes per trace
                observability.set_counter(
                    f"grad_sync.bucket{i:02d}.plan_bytes", nbytes)
        if self.param_gather_buckets is not None:
            sizes = sharding_utils.bucket_bytes(pf_shapes,
                                                self.param_gather_buckets)
            observability.set_counter("param_gather.n_buckets",
                                      len(self.param_gather_buckets))
            observability.set_counter("param_gather.total_bytes", sum(sizes))
            for i, nbytes in enumerate(sizes):
                observability.set_counter(
                    f"param_gather.bucket{i:02d}.plan_bytes", nbytes)

        def island_loss_grads(train_params, frozen_params, buffers, batch,
                              rng):
            from .._compat import shard_map
            n_tot = 1
            for ax in sync_axes:
                n_tot *= mesh.shape[ax]

            def local(train_params, frozen_params, buffers, batch, rng):
                idx = lax.axis_index(sync_axes[0])
                for ax in sync_axes[1:]:
                    idx = idx * mesh.shape[ax] + lax.axis_index(ax)
                rng_local = jax.random.fold_in(rng, idx)
                (loss, new_buf), grads = accum_loss_grads(
                    train_params, frozen_params, buffers, batch, rng_local,
                    use_hints=False)
                if buckets_ref is not None:
                    grads = sharding_utils.bucketed_psum(
                        grads, buckets_ref, sync_axes)
                else:
                    grads = {k: lax.psum(g, sync_axes)
                             for k, g in grads.items()}
                grads = {k: g / n_tot for k, g in grads.items()}
                loss = lax.psum(loss, sync_axes) / n_tot
                new_buf = {k: lax.psum(v, sync_axes) / n_tot
                           for k, v in new_buf.items()}
                return loss, new_buf, grads

            bs = list(bspec)
            batch_specs = jax.tree_util.tree_map(
                lambda a: P(*(bs + [None] * (a.ndim - len(bs)))), batch)
            f = shard_map(local, mesh=mesh,
                          in_specs=(P(), P(), P(), batch_specs, P()),
                          out_specs=(P(), P(), P()),
                          axis_names=frozenset(mesh.axis_names),
                          check_vma=False)
            loss, new_buf, grads = f(train_params, frozen_params, buffers,
                                     batch, rng)
            return (loss, new_buf), grads

        def step_fn(train_params, opt_states, buffers, frozen_params, batch,
                    rng, lr):
            # stage-3 prefetch: hand the forward the GATHERED view (bucketed,
            # one ahead); the optimizer update below stays on the sharded
            # originals. Constraints are value-identity, so grads wrt the
            # gathered view equal grads wrt the originals bit-for-bit.
            fwd_params = train_params
            if pf_buckets_ref:
                fwd_params = sharding_utils.prefetch_param_gathers(
                    train_params, pf_buckets_ref, pf_shardings_ref)
            if sync_axes:
                (loss, new_buffers), grads = island_loss_grads(
                    fwd_params, frozen_params, buffers, batch, rng)
            else:
                (loss, new_buffers), grads = accum_loss_grads(
                    fwd_params, frozen_params, buffers, batch, rng)
            if grad_shardings_ref:
                grads = {
                    k: jax.lax.with_sharding_constraint(
                        g, grad_shardings_ref[k])
                    if k in grad_shardings_ref else g
                    for k, g in grads.items()}
            if clip_norm is not None:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)))
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = jax.tree_util.tree_map(
                    lambda g: (g * scale).astype(g.dtype), grads)
            new_params = dict(train_params)
            new_states = dict(opt_states)
            for k in trainable_keys:
                p32 = train_params[k]
                new_p, new_s = update_rule(
                    p32.astype(jnp.float32) if p32.dtype != jnp.float32 else p32,
                    grads[k], opt_states[k], lr, wd_map[k], {})
                new_p = new_p.astype(train_params[k].dtype)
                if param_shardings_ref is not None:
                    # keep the param on its declared layout: replicated for
                    # ZeRO-1/2 (gathers the sharded update), sharded for
                    # ZeRO-3/TP — the reference's post-step broadcast
                    new_p = jax.lax.with_sharding_constraint(
                        new_p, param_shardings_ref[k])
                new_params[k] = new_p
                new_states[k] = new_s
            return new_params, new_states, new_buffers, loss

        donate_args = (0, 1, 2) if donate else ()
        self._compiled = jax.jit(step_fn, donate_argnums=donate_args)

    def _prepare(self, inputs, labels):
        """Shared __call__/compiled_hlo preamble: the batch pytree and the
        param split, exactly as the compiled step consumes them."""
        if labels is None:
            *inputs, labels = inputs
            labels = [labels]
        elif not isinstance(labels, (list, tuple)):
            labels = [labels]
        batch = {
            "inputs": tuple(self._place_batch(x) for x in inputs),
            "labels": [self._place_batch(l) for l in labels],
        }
        train_params = {k: self.params[k] for k in self.trainable_keys}
        frozen = {k: v for k, v in self.params.items()
                  if k not in set(self.trainable_keys)}
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return batch, train_params, frozen, lr

    def __call__(self, *inputs, labels=None):
        batch, train_params, frozen, lr = self._prepare(list(inputs), labels)
        self._rng, sub = jax.random.split(self._rng)
        m = self.telemetry
        led = self.ledger
        captured = False
        if (m is not None or led is not None) and self._flops_stale:
            # once per (re)compile, BEFORE dispatch (donation hasn't consumed
            # the buffers yet): lower the step for this batch and read the
            # program's cost analysis — trace-time work, nothing per step.
            # The trace also fires every pallas_call cost_estimate= site, so
            # the ledger ingests exact per-kernel FLOPs/bytes for free.
            self._capture_cost(train_params, frozen, batch, sub, lr)
            captured = True
        rec = self.recorder
        fl = self.fleet
        timed = (m is not None or rec is not None or fl is not None
                 or led is not None)
        t0 = time.perf_counter() if timed else 0.0
        try:
            new_p, new_s, new_b, loss = self._compiled(
                train_params, self.opt_states, self.buffers, frozen, batch,
                sub, lr)
        except BaseException:
            # crash post-mortem: flush the last N dispatch records before
            # the exception propagates (no-op without a telemetry dir)
            if rec is not None:
                rec.dump("exception")
            raise
        if timed:
            dt = time.perf_counter() - t0
            is_compile = (self._note_compile()
                          if (m is not None or led is not None)
                          else self._step_count == 0)
            if is_compile and captured:
                # this dispatch paid trace+compile. A recompile marks FLOPs
                # stale (the program changed) — unless they were captured
                # for exactly this program a few lines up.
                self._flops_stale = False
            if m is not None:
                if is_compile:
                    # account it as compile time, not a step sample
                    m.record_compile(compile_s=dt, flops=m.flops_per_step)
                else:
                    m.step(tokens=self._batch_tokens(batch),
                           dispatch_ms=dt * 1e3)
            if rec is not None:
                if is_compile:
                    rec.record_compile("train_step", dt)
                else:
                    # dispatch wall time (async): in steady state with
                    # donation it tracks device step time; a spike means a
                    # recompile, host stall, or device-queue backup
                    rec.record({"iteration": self._step_count + 1,
                                "dispatch_ms": dt * 1e3,
                                "tokens": self._batch_tokens(batch)})
                    rec.check_step_time(dt)
            if fl is not None and not is_compile:
                # host float only — the monitor must never pull a device
                # value (that would be the sync this path avoids)
                fl.on_step(dt)
            if led is not None and not is_compile:
                led.on_step(dt)
                if observability.ledger_dir() \
                        and self._step_count % 64 == 0:
                    led.write()
        self.params.update(new_p)
        self.opt_states = new_s
        self.buffers = new_b
        self._step_count += 1
        if self.checkpoint is not None:
            # interval-paced async save (overlaps the next steps) and the
            # preemption hook: a pending SIGTERM raises Preempted here,
            # after the final sync save and flight-recorder dump
            self.checkpoint.on_step(self._step_count, self.state_dict,
                                    recorder=self.recorder)
        return Tensor._from_data(loss)

    def state_dict(self):
        """Checkpointable state: params, optimizer states, buffers and the
        step counter, as raw (possibly sharded) jax arrays. Restoring via
        CheckpointManager.restore reshards each leaf onto whatever
        sharding THIS TrainStep placed it with — the elastic-resume path
        when the mesh shape changed between save and restore."""
        return {"params": dict(self.params),
                "opt_states": self.opt_states,
                "buffers": dict(self.buffers),
                "step": self._step_count}

    def load_state_dict(self, state):
        """Adopt a (restored) state dict produced by :meth:`state_dict`."""
        self.params.update(state["params"])
        self.opt_states = state["opt_states"]
        self.buffers.update(state["buffers"])
        self._step_count = int(np.asarray(state["step"]))  # noqa: PTA006 -- restore boundary, once per resume: the step counter must become a host int

    def restore(self, checkpoint=None, step: Optional[int] = None) -> int:
        """Restore from `checkpoint` (defaults to the ctor's manager):
        fills a fresh state_dict() — current shardings as reshard targets —
        and adopts it. Returns the restored step number."""
        mgr = checkpoint if checkpoint is not None else self.checkpoint
        if mgr is None:
            raise ValueError("no CheckpointManager: pass checkpoint= to "
                             "restore() or the TrainStep constructor")
        state = self.state_dict()
        restored = mgr.restore(state, step=step)
        self.load_state_dict(state)
        return restored

    def _capture_cost(self, train_params, frozen, batch, sub, lr):
        """FLOPs-per-step from the lowered program's cost analysis (client-
        side HLO analysis; no extra XLA compile, no device work). Tracing
        also fires every pallas_call ``cost_estimate=`` site exactly as
        many times as the program calls it, so the window delta over the
        kernel-cost totals is this program's exact per-kernel cost — the
        roofline ledger's model-mode feed."""
        self._flops_stale = False
        try:
            from ..ops import _common as _opsc
            snap = _opsc.snapshot_kernel_costs()
            t0 = time.perf_counter()
            lowered = self._compiled.lower(train_params, self.opt_states,
                                           self.buffers, frozen, batch, sub,
                                           lr)
            trace_s = time.perf_counter() - t0
            if self.ledger is not None:
                self.ledger.ingest(_opsc.kernel_costs_since(snap))
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float((cost or {}).get("flops", 0.0))
            if self.telemetry is not None:
                self.telemetry.trace_time_s += trace_s
                if flops > 0:
                    self.telemetry.flops_per_step = flops
        except Exception:
            pass

    def _note_compile(self) -> bool:
        """Detect a fresh jit compile via the pjit cache size (True exactly
        when this call compiled); marks FLOPs stale on recompiles."""
        try:
            size = self._compiled._cache_size()
        except Exception:
            if self.telemetry is None:
                return not self._step_count
            return self.telemetry.compiles == 0 and not self._step_count
        if size != self._seen_cache_size:
            self._seen_cache_size = size
            self._flops_stale = True
            return True
        return False

    def _batch_tokens(self, batch) -> Optional[int]:
        """Tokens per step for throughput: [B, S] integer inputs count B*S
        (sequence ids), anything else counts batch rows. Override with the
        ``tokens_per_step`` ctor arg."""
        if self.telemetry is not None \
                and self.telemetry.tokens_per_step is not None:
            return self.telemetry.tokens_per_step
        try:
            x = batch["inputs"][0]
            if x.ndim == 2 and jnp.issubdtype(x.dtype, jnp.integer):
                return int(x.shape[0]) * int(x.shape[1])
            return int(x.shape[0])
        except Exception:
            return None

    def compiled_hlo(self, *inputs, labels=None) -> str:
        """Post-SPMD-partitioning HLO of the step (for inspecting which
        collectives XLA emitted — e.g. ZeRO stage-2's grad reduce-scatter)."""
        batch, train_params, frozen, lr = self._prepare(list(inputs), labels)
        lowered = self._compiled.lower(train_params, self.opt_states,
                                       self.buffers, frozen, batch,
                                       self._rng, lr)
        return lowered.compile().as_text()

    def _place_batch(self, x):
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))  # noqa: PTA006 -- input boundary: stages the host batch, not a device pull
        if self.mesh is not None:
            if self.batch_spec is not None:
                spec = list(self.batch_spec) + \
                    [None] * (arr.ndim - len(self.batch_spec))
            else:
                # no dp sharding: the batch must still live on the MESH
                # (replicated) — mesh-sharded params + single-device
                # batch is an incompatible-devices error under jit
                spec = [None] * arr.ndim
            arr = jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))
        return arr

    def sync_to_model(self):
        """Copy the (device, possibly sharded) params back into the Layer."""
        for k, p in self.param_objs.items():
            if k in self.params:
                p._data = self.params[k]
        for k, b in self.model.named_buffers():
            if b is not None and k in self.buffers:
                b._data = self.buffers[k]


class _mesh_hints:
    """Context activating sharding hints for the functional trace."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._cm = None

    def __enter__(self):
        self._cm = sharding_utils.auto_shard(self.mesh)
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)
