"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        maxk = max(self.topk)
        topk_idx = np.argsort(-p, axis=-1)[..., :maxk]
        correct = topk_idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        n = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            self.total[i] += float(num)
            self.count[i] += n
            accs.append(float(num) / max(n, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)) > 0.5
        l = (labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)) > 0.5
        self.tp += int(np.sum(p & l))
        self.fp += int(np.sum(p & ~l))

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)) > 0.5
        l = (labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)) > 0.5
        self.tp += int(np.sum(p & l))
        self.fn += int(np.sum(~p & l))

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.ravel()
        l = l.ravel()
        bins = np.minimum((p * self.num_thresholds).astype(int), self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    from ..tensor.tensor import _run_op
    def f(p, l):
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        topk = jnp.argsort(-p, axis=-1)[..., :k]
        corr = (topk == l[..., None]).any(-1)
        return corr.astype(jnp.float32).mean()
    return _run_op("accuracy", f, (input, label), {})
