"""Model zoo: flagship configs from the BASELINE ladder."""
from . import llama
from . import ernie_moe, gpt2
from .llama import (LlamaConfig, ParallelConfig, build_train_step,
                    init_llama_params, llama_loss, llama_7b, llama_13b,
                    llama_tiny, count_params)
