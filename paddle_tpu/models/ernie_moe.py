"""ERNIE-style MoE transformer with expert parallelism (BASELINE config 5:
"ERNIE-MoE with Fleet expert-parallel + PipelineLayer").

Ref: the reference composes incubate MoELayer (gshard gate +
global_scatter/global_gather all-to-all) with fleet PP. TPU-native: the same
functional-core design as models/llama.py, with every even layer's FFN
replaced by a top-2 MoE block whose expert stack is sharded over the 'ep'
submesh — the dispatch einsum becomes XLA all-to-all over ICI.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.moe import (RATIO_STAT_KEYS, default_dispatch_mode,
                            moe_dispatch_combine, zero_routing_stats)
from ..ops.rms_norm import fused_rms_norm
from .llama import _adamw_init, _adamw_update


@dataclasses.dataclass
class ErnieMoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_experts: int = 8
    moe_topk: int = 2
    # shared experts (fine-grained MoE, PR 10): dense FFN expert(s) every
    # token passes through IN ADDITION to its routed top-k experts —
    # one fused [H, n_shared*I] matmul pair, replicated across the mesh
    num_shared_experts: int = 0
    capacity_factor: float = 1.25
    moe_every: int = 2           # every k-th layer is MoE
    aux_loss_weight: float = 0.01
    max_position_embeddings: int = 512
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # "capacity" (reference drop parity, default) | "ragged" (dropless
    # grouped-GEMM, ep-replicated tokens + combine psum) | "ragged_a2a"
    # (dropless + tokens sharded over ep with the ragged all-to-all
    # dispatch, PR 10) | None -> PADDLE_TPU_MOE_DROPLESS env default
    dispatch_mode: Optional[str] = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def ernie_moe_tiny():
    return ErnieMoEConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=4, num_attention_heads=4,
                          num_experts=4, max_position_embeddings=128,
                          dtype=jnp.float32)


def ernie_moe_fine():
    """Fine-grained + shared-expert preset (PR 10): many SMALL experts
    (E=32, top-4, expert I = H/2) plus one always-on shared expert — the
    regime where routing skew is the norm and the ragged a2a dispatch
    matters most. Dispatches via "ragged_a2a" (tokens sharded over ep)."""
    return ErnieMoEConfig(vocab_size=8192, hidden_size=1024,
                          intermediate_size=512, num_hidden_layers=8,
                          num_attention_heads=16, num_experts=32,
                          moe_topk=4, num_shared_experts=1,
                          max_position_embeddings=1024,
                          dtype=jnp.bfloat16, dispatch_mode="ragged_a2a")


def ernie_moe_fine_tiny():
    """CPU-sized ernie_moe_fine: same shape family (fine-grained experts,
    one shared expert, ragged_a2a dispatch) at dryrun/test scale."""
    return ErnieMoEConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=32, num_hidden_layers=4,
                          num_attention_heads=4, num_experts=8, moe_topk=2,
                          num_shared_experts=1, max_position_embeddings=128,
                          dtype=jnp.float32, dispatch_mode="ragged_a2a")


def init_params(config: ErnieMoEConfig, seed: int = 0):
    c = config
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 12)
    d = c.dtype
    std = 0.02
    L = c.num_hidden_layers
    E = c.num_experts

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(d)

    def shared_block(n):
        # keys derive from the (previously unused) ks[11] so adding
        # shared experts never perturbs the existing parameter draws
        sk1, sk2 = jax.random.split(ks[11])
        si = c.num_shared_experts * c.intermediate_size
        return {"s_w1": rnd(sk1, (n, c.hidden_size, si)),
                "s_w2": rnd(sk2, (n, si, c.hidden_size))}

    def attn_block(n, k1, k2):
        return {
            "ln1": jnp.ones((n, c.hidden_size), d),
            "qkv": rnd(k1, (n, c.hidden_size, 3 * c.hidden_size)),
            "o": rnd(k2, (n, c.hidden_size, c.hidden_size)),
            "ln2": jnp.ones((n, c.hidden_size), d),
        }

    if _split_stacks(c):
        # SPLIT stacks: dense layers carry ONLY dense FFN weights, MoE
        # layers ONLY expert weights. The old single [L, ...] layout
        # allocated e_w1/e_w2 for every layer (537M dead params at the
        # bench shape) whose f32 AdamW moments streamed ~15 GB of HBM
        # per step — the r4 "dispatch dominates" diagnosis was half the
        # story; the optimizer streaming dead state was the other half.
        n = L // 2
        layers = {
            "dense": {**attn_block(n, ks[1], ks[2]),
                      "w1": rnd(ks[3], (n, c.hidden_size,
                                        c.intermediate_size)),
                      "w2": rnd(ks[4], (n, c.intermediate_size,
                                        c.hidden_size))},
            "moe": {**attn_block(n, ks[9], ks[10]),
                    "gate": rnd(ks[5], (n, c.hidden_size, E))
                    .astype(jnp.float32),
                    "e_w1": rnd(ks[6], (n, E, c.hidden_size,
                                        c.intermediate_size)),
                    "e_w2": rnd(ks[7], (n, E, c.intermediate_size,
                                        c.hidden_size)),
                    **(shared_block(n) if c.num_shared_experts else {})},
        }
    else:
        layers = {
            **attn_block(L, ks[1], ks[2]),
            "w1": rnd(ks[3], (L, c.hidden_size, c.intermediate_size)),
            "w2": rnd(ks[4], (L, c.intermediate_size, c.hidden_size)),
            "gate": rnd(ks[5], (L, c.hidden_size, E)).astype(jnp.float32),
            "e_w1": rnd(ks[6], (L, E, c.hidden_size, c.intermediate_size)),
            "e_w2": rnd(ks[7], (L, E, c.intermediate_size, c.hidden_size)),
            **(shared_block(L) if c.num_shared_experts else {}),
        }
    return {
        "embed": rnd(ks[0], (c.vocab_size, c.hidden_size)),
        "pos": rnd(ks[8], (c.max_position_embeddings, c.hidden_size)),
        "layers": layers,
        "final_ln": jnp.ones((c.hidden_size,), d),
    }


def _split_stacks(config):
    """Split dense/moe layer stacks (see init_params) — the standard
    every-other-layer ERNIE layout."""
    return config.moe_every == 2 and config.num_hidden_layers % 2 == 0


def param_pspecs(config, ep_degree: int, dp_degree: int = 1):
    ep = "ep" if ep_degree > 1 else None
    attn = {
        "ln1": P(None, None),
        "qkv": P(None, None, None),
        "o": P(None, None, None),
        "ln2": P(None, None),
    }
    dense = {"w1": P(None, None, None), "w2": P(None, None, None)}
    moe = {
        "gate": P(None, None, None),
        "e_w1": P(None, ep, None, None),   # experts sharded over 'ep'
        "e_w2": P(None, ep, None, None),
    }
    if config.num_shared_experts:
        # shared experts run on every token on every rank: replicated
        moe["s_w1"] = P(None, None, None)
        moe["s_w2"] = P(None, None, None)
    if _split_stacks(config):
        layers = {"dense": {**attn, **dense}, "moe": {**attn, **moe}}
    else:
        layers = {**attn, **dense, **moe}
    return {"embed": P(None, None), "pos": P(None, None), "layers": layers,
            "final_ln": P(None)}


def _attn_and_norm(p, h, config: ErnieMoEConfig):
    c = config
    b, s, hid = h.shape
    nh, hd = c.num_attention_heads, c.head_dim
    x = fused_rms_norm(h, p["ln1"], c.layer_norm_eps)
    qkv = (x @ p["qkv"]).reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    from ..ops._common import interpret_mode
    if interpret_mode():
        from ..nn.functional.attention import _xla_sdpa
        attn = _xla_sdpa(q, k, v, is_causal=True)
    else:
        from ..ops.flash_attention import flash_attention_bshd
        attn = flash_attention_bshd(q, k, v, causal=True)
    h = h + attn.reshape(b, s, hid) @ p["o"]
    return h, fused_rms_norm(h, p["ln2"], c.layer_norm_eps)


def _moe_ffn(p, x_, config: ErnieMoEConfig, use_onehot=False,
             mesh=None, with_stats=False, dispatch_mode="capacity"):
    c = config
    hid = x_.shape[-1]
    tokens = x_.reshape(-1, hid)

    def expert_fn(params, toks):
        w1, w2 = params
        return jax.nn.gelu(toks @ w1) @ w2

    if use_onehot and mesh is not None:
        # ep>1 with the SLOT schedule (r5): a fully-manual shard_map
        # island over (dp, ep) — each shard routes its local tokens,
        # gathers only its local experts' slots, and the combine psums
        # [T,D] partials over 'ep'. Capacity is per-dp-shard (the
        # reference's MoE also sizes capacity from the local batch);
        # with no drops this is numerically identical to serial, which
        # the ep-vs-serial tests assert. dispatch_mode="ragged" swaps
        # the local expert compute for the DROPLESS grouped-GEMM path
        # (moe_ragged_dispatch_local) — the combine psum is unchanged.
        # dispatch_mode="ragged_a2a" (PR 10) shards the TOKENS over ep
        # too and moves only each destination's actual rows via the
        # ragged all-to-all — no token replication, no combine psum.
        # The one-hot einsum fallback below stays for mesh-less callers.
        from .._compat import shard_map
        from ..parallel.moe import (moe_ragged_dispatch_a2a,
                                    moe_ragged_dispatch_local,
                                    moe_slot_dispatch_local)
        a2a = dispatch_mode == "ragged_a2a"
        tok_spec = P(("dp", "ep"), None) if a2a else P("dp", None)

        def island(tok, gate, w1, w2):
            logits = tok.astype(jnp.float32) @ gate
            if a2a:
                res = moe_ragged_dispatch_a2a(
                    tok, logits, w1, w2, c.num_experts,
                    axis_name="ep", k=c.moe_topk,
                    return_stats=with_stats)
            elif dispatch_mode == "ragged":
                res = moe_ragged_dispatch_local(
                    tok, logits, w1, w2, c.num_experts,
                    axis_name="ep", k=c.moe_topk,
                    return_stats=with_stats)
            else:
                res = moe_slot_dispatch_local(
                    tok, logits, expert_fn, (w1, w2), c.num_experts,
                    axis_name="ep", k=c.moe_topk,
                    capacity_factor=c.capacity_factor,
                    return_stats=with_stats)
            # aux is computed from LOCAL tokens: average over the axes
            # the tokens shard over so the P() out-spec is genuinely
            # replicated (per-shard balance loss, averaged)
            aux_axes = ("dp", "ep") if a2a else "dp"
            if with_stats:
                out, aux, st = res
                # stats are per-dp-shard (the local paths replicate
                # them over ep; the a2a path psums over ep inside):
                # counts sum over dp (whole-batch totals), ratio keys
                # average over dp
                st = {k_: (lax.pmean(v, "dp") if k_ in RATIO_STAT_KEYS
                           else lax.psum(v, "dp"))
                      for k_, v in st.items()}
                return out, lax.pmean(aux, aux_axes), st
            out, aux = res
            return out, lax.pmean(aux, aux_axes)

        stats_spec = jax.tree_util.tree_map(
            lambda _: P(), zero_routing_stats(dispatch_mode,
                                              c.num_experts))
        out_specs = ((tok_spec, P(), stats_spec) if with_stats
                     else (tok_spec, P()))
        res = shard_map(
            island, mesh=mesh,
            in_specs=(tok_spec, P(None, None),
                      P("ep", None, None), P("ep", None, None)),
            out_specs=out_specs,
            check_vma=False)(tokens, p["gate"], p["e_w1"], p["e_w2"])
        out, aux = res[0], res[1]
        stats = res[2] if with_stats else None
    else:
        logits = tokens.astype(jnp.float32) @ p["gate"]
        # mesh-less / ep=1 "ragged_a2a" degenerates to the serial ragged
        # path (the a2a combine is bitwise-equal to it by construction);
        # zero wire stats keep the key set consistent
        serial_mode = ("ragged" if dispatch_mode == "ragged_a2a"
                       else dispatch_mode)
        res = moe_dispatch_combine(tokens, logits, expert_fn,
                                   (p["e_w1"], p["e_w2"]),
                                   c.num_experts, k=c.moe_topk,
                                   capacity_factor=c.capacity_factor,
                                   use_onehot=use_onehot,
                                   return_stats=with_stats,
                                   dispatch_mode=serial_mode)
        out, aux = res[0], res[1]
        stats = res[2] if with_stats else None
        if stats is not None and dispatch_mode == "ragged_a2a":
            z = jnp.zeros((), jnp.float32)
            stats = {**stats, "moe_a2a_wire_rows": z,
                     "moe_a2a_buffer_rows": z}
    if c.num_shared_experts:
        # shared expert(s): a dense FFN every token passes through, added
        # to the routed combine (fine-grained MoE; replicated weights)
        shared = jax.nn.gelu(tokens @ p["s_w1"]) @ p["s_w2"]
        out = out + shared.astype(out.dtype)
    out = out.reshape(x_.shape).astype(x_.dtype)
    if with_stats:
        return out, aux.astype(jnp.float32), stats
    return out, aux.astype(jnp.float32)


def _dense_ffn(p, x_, config: ErnieMoEConfig, with_stats=False,
               dispatch_mode="capacity"):
    out = (jax.nn.gelu(x_ @ p["w1"]) @ p["w2"]).astype(x_.dtype)
    if with_stats:
        # zero stats must match the MoE branch's key set (lax.cond pytree)
        return out, jnp.zeros((), jnp.float32), zero_routing_stats(
            dispatch_mode, config.num_experts)
    return out, jnp.zeros((), jnp.float32)


def _layer_static(p, h, is_moe, config: ErnieMoEConfig, use_onehot=False,
                  mesh=None, with_stats=False, dispatch_mode="capacity"):
    """One decoder layer with a STATIC moe/dense choice (no lax.cond)."""
    h, x = _attn_and_norm(p, h, config)
    res = (_moe_ffn(p, x, config, use_onehot, mesh, with_stats,
                    dispatch_mode) if is_moe
           else _dense_ffn(p, x, config, with_stats, dispatch_mode))
    if with_stats:
        ffn_out, aux, stats = res
        return h + ffn_out, aux, stats
    ffn_out, aux = res
    return h + ffn_out, aux


def _layer(p, h, layer_idx, config: ErnieMoEConfig, use_onehot=False,
           mesh=None, with_stats=False, dispatch_mode="capacity"):
    c = config

    def moe_branch(x_):
        return _moe_ffn(p, x_, c, use_onehot, mesh, with_stats,
                        dispatch_mode)

    def dense_branch(x_):
        return _dense_ffn(p, x_, c, with_stats, dispatch_mode)

    h, x = _attn_and_norm(p, h, c)
    is_moe = (layer_idx % c.moe_every) == (c.moe_every - 1)
    # layer_idx is a traced scan counter: lax.cond keeps one compiled body
    res = lax.cond(is_moe, moe_branch, dense_branch, x)
    if with_stats:
        ffn_out, aux, stats = res
        return h + ffn_out, aux, stats
    ffn_out, aux = res
    return h + ffn_out, aux


def moe_loss(params, ids, labels, config: ErnieMoEConfig,
             use_onehot=False, mesh=None, with_stats=False,
             dispatch_mode="capacity", active_rows=False):
    # use_onehot marks ep>1: WITH a mesh the slot-schedule shard_map
    # island runs (see _moe_ffn); the one-hot einsum only serves
    # mesh-less callers as a fallback
    #
    # with_stats=True: the aux output becomes (lm_loss, stats) where stats
    # aggregates per-layer routing_stats over the MoE layers — counts
    # (dropped/routed) sum, ratios (imbalance/util) average. Stats are
    # lax.stop_gradient'd so the loss/grads are bit-identical either way.
    #
    # active_rows=True (PR 10): additionally return the PER-LAYER
    # [n_moe_layers, E] routed-row counts (un-summed moe_expert_rows) as
    # the last aux element, for the active-only optimizer masking in
    # build_train_step. Requires a ragged dispatch mode whose stats
    # carry moe_expert_rows.
    c = config
    ws = with_stats or active_rows
    b, s = ids.shape
    h = (jnp.take(params["embed"], ids, axis=0)
         + params["pos"][:s][None]).astype(c.dtype)

    # remat per scan step: the capacity-bucketed dispatch one-hots are
    # large and per-layer; recomputing them in the backward trades cheap
    # FLOPs for the activation memory that OOMed real-sized configs
    if _split_stacks(c):
        # the moe/dense pattern is STATIC: scan over (dense, moe) layer
        # PAIRS with both bodies inline — the traced-idx lax.cond was the
        # single largest span in the profiled step (it blocks fusion
        # across the ffn boundary and carries both branches). Stacks are
        # SPLIT (see init_params): each kind streams only its own weights.
        def pair_body(h, lp):
            p0, p1 = lp
            h, aux0 = _layer_static(p0, h, False, c)
            res = _layer_static(p1, h, True, c, use_onehot, mesh,
                                ws, dispatch_mode)
            if ws:
                h, aux1, stats = res
                return h, (aux0 + aux1,
                           jax.lax.stop_gradient(stats))
            h, aux1 = res
            return h, aux0 + aux1

        # checkpoint_dots: matmul outputs survive the remat boundary, so
        # the backward's re-forward is elementwise-only (measured -3 ms
        # per step vs full remat at the bench shape; the saved dot
        # residuals are well within HBM at these sizes)
        h, ys = lax.scan(
            jax.checkpoint(pair_body,
                           policy=jax.checkpoint_policies.checkpoint_dots),
            h, (params["layers"]["dense"], params["layers"]["moe"]))
    else:
        def body(carry, inp):
            h = carry
            idx, layer_params = inp
            res = _layer(layer_params, h, idx, c, use_onehot, mesh,
                         ws, dispatch_mode)
            if ws:
                h, aux, stats = res
                return h, (aux, jax.lax.stop_gradient(stats))
            h, aux = res
            return h, aux

        idxs = jnp.arange(c.num_hidden_layers)
        h, ys = lax.scan(jax.checkpoint(body), h,
                         (idxs, params["layers"]))
    rows_pl = None
    if ws:
        auxes, layer_stats = ys
        if active_rows:
            if "moe_expert_rows" not in layer_stats:
                raise ValueError(
                    "active_rows requires a dispatch mode whose stats "
                    "carry moe_expert_rows (ragged / ragged_a2a), got "
                    f"{dispatch_mode!r}")
            rows_pl = layer_stats["moe_expert_rows"]  # [n_moe_layers, E]
        n_moe = jnp.maximum(
            (layer_stats["moe_routed_tokens"]
             + layer_stats["moe_dropped_tokens"] > 0)
            .astype(jnp.float32).sum(), 1.0)
        # generic over the key set (capacity vs ragged): counts sum over
        # layers, ratio keys average over the layers that actually routed
        stats = {k: (v.sum(0) / n_moe if k in RATIO_STAT_KEYS
                     else v.sum(0))
                 for k, v in layer_stats.items()}
    else:
        auxes = ys
    x = fused_rms_norm(h, params["final_ln"], c.layer_norm_eps)
    logits = (x @ params["embed"].T).astype(jnp.float32)
    mask = labels != -100
    safe = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    lm_loss = jnp.sum(jnp.where(mask, -picked, 0.0)) / jnp.maximum(mask.sum(), 1)
    total = lm_loss + c.aux_loss_weight * auxes.sum()
    base = (lm_loss, stats) if with_stats else lm_loss
    if active_rows:
        return total, (base, rows_pl)
    return total, base


def _none_like(tree):
    if isinstance(tree, dict):
        return {k: _none_like(v) for k, v in tree.items()}
    return None


def _expert_row_masks(params, rows_pl):
    """Masks pytree for ``_adamw_update(masks=)`` (PR 10): experts with
    zero routed tokens this step keep their params and AdamW moments
    bitwise-frozen (lazy/sparse-Adam; see llama._adamw_update).

    ``rows_pl`` is the per-layer [n_moe_layers, E] routed-row counts from
    ``moe_loss(active_rows=True)`` — its leading dim lines up with the
    stacked expert weights, so ``rows_pl > 0`` broadcasts as the row
    mask for ``e_w1``/``e_w2``. Every other leaf stays None (unmasked).
    """
    active = rows_pl > 0
    masks = _none_like(params)
    if "moe" in params["layers"]:  # split dense/moe pair stacks
        masks["layers"]["moe"]["e_w1"] = active
        masks["layers"]["moe"]["e_w2"] = active
    else:
        masks["layers"]["e_w1"] = active
        masks["layers"]["e_w2"] = active
    return masks


def build_train_step(config: ErnieMoEConfig, ep_degree: int = 1,
                     dp_degree: int = 1, mesh: Optional[Mesh] = None,
                     lr: float = 3e-4, seed: int = 0,
                     with_stats: bool = False,
                     dispatch_mode: Optional[str] = None,
                     multi_precision: bool = True,
                     active_only_moments: bool = False):
    """EP x DP training step; experts sharded over 'ep', batch over 'dp'.

    with_stats=True: the step's 4th output becomes a dict
    ``{"lm_loss": ..., **routing_stats}`` of on-device f32 values
    (aggregated over layers and the dp axis) instead of the bare
    lm_loss — routing telemetry rides the step outputs, no extra sync.
    The stats key set follows the dispatch mode (capacity: drops /
    routed / imbalance / capacity-util scalars; ragged: explicit
    drops=0, live/padded rows, [E] per-expert group sizes).

    dispatch_mode: "capacity" (default), "ragged" (dropless grouped
    GEMM), or None -> config.dispatch_mode -> PADDLE_TPU_MOE_DROPLESS
    env default.

    multi_precision: True (reference default) keeps f32 AdamW moments;
    False stores moments in each param's dtype — on a bf16 expert stack
    that halves the optimizer HBM streaming the r5 verdict flagged.

    active_only_moments: True (PR 10) masks the AdamW moment
    read-modify-write for experts that routed ZERO tokens this step
    (mask from the moe_expert_rows routing stats; requires a ragged
    dispatch mode). Touched experts update bitwise-identically to the
    full pass; untouched experts keep params AND moments frozen —
    under skew this skips the moment streaming for cold experts."""
    if dispatch_mode is None:
        dispatch_mode = config.dispatch_mode
    if dispatch_mode is None:
        dispatch_mode = default_dispatch_mode()
    if mesh is None and ep_degree * dp_degree > 1:
        from ..distributed.fleet.topology import _pick_devices
        devs = _pick_devices(ep_degree * dp_degree)
        mesh = Mesh(np.array(devs).reshape(dp_degree, ep_degree),
                    axis_names=("dp", "ep"))

    params = init_params(config, seed)
    pspecs = param_pspecs(config, ep_degree, dp_degree)
    if mesh is not None:
        params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, pspecs, is_leaf=lambda x: not isinstance(x, dict))
    opt = _adamw_init(params, multi_precision=multi_precision)

    use_onehot = ep_degree > 1
    moe_mesh = mesh if ep_degree > 1 else None

    def step(p, o, ids, labels):
        (loss, aux), grads = jax.value_and_grad(
            moe_loss, has_aux=True)(p, ids, labels, config, use_onehot,
                                    moe_mesh, with_stats, dispatch_mode,
                                    active_only_moments)
        masks = None
        if active_only_moments:
            aux, rows_pl = aux
            masks = _expert_row_masks(p, rows_pl)
        new_p, new_o = _adamw_update(p, grads, o, lr, masks=masks)
        if with_stats:
            lm_loss, stats = aux
            return new_p, new_o, loss, {"lm_loss": lm_loss, **stats}
        return new_p, new_o, loss, aux

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    batch_sharding = (NamedSharding(mesh, P("dp", None))
                      if mesh is not None else None)

    def step_fn(p, o, ids, labels):
        ids = jnp.asarray(ids, jnp.int32)
        labels = jnp.asarray(labels, jnp.int32)
        if batch_sharding is not None:
            ids = jax.device_put(ids, batch_sharding)
            labels = jax.device_put(labels, batch_sharding)
        return jit_step(p, o, ids, labels)

    return step_fn, params, opt
