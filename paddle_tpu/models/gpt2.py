"""GPT-2 built on the fleet TP layers (BASELINE config 2: GPT-2 345M TP=2).

Ref: the reference exercises Column/RowParallelLinear with GPT-2 in
test/collective/fleet. This is the Layer-based (dygraph) model — it runs
eagerly dense, and compiled over a mesh the TP specs on its fleet layers
partition it; build_gpt2_train_step wires it into the jit TrainStep.
"""
from __future__ import annotations

import math

import numpy as np

from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..nn import Dropout, Embedding, Layer, LayerList, LayerNorm
from ..nn import functional as F
from ..nn.layer.layers import ParamAttr
from ..nn import initializer as I
from ..tensor import arange, reshape
from ..tensor.tensor import Tensor


class GPT2Config:
    def __init__(self, vocab_size=50304, hidden_size=1024, num_layers=24,
                 num_heads=16, intermediate_size=None, max_position=1024,
                 dropout=0.0, layer_norm_eps=1e-5):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position = max_position
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps


def gpt2_345m():
    return GPT2Config(hidden_size=1024, num_layers=24, num_heads=16)


def gpt2_tiny():
    return GPT2Config(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, max_position=128)


class GPT2Attention(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        init = ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.qkv_proj = ColumnParallelLinear(c.hidden_size, 3 * c.hidden_size,
                                             weight_attr=init,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(c.hidden_size, c.hidden_size,
                                          weight_attr=init,
                                          input_is_parallel=True)
        self.dropout = Dropout(c.dropout)

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = reshape(attn, [b, s, self.num_heads * self.head_dim])
        return self.dropout(self.out_proj(attn))


class GPT2MLP(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.fc_in = ColumnParallelLinear(config.hidden_size,
                                          config.intermediate_size,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size,
                                        config.hidden_size,
                                        weight_attr=init,
                                        input_is_parallel=True)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers \
            import fused_ffn_plan
        from ..parallel.collective_matmul import gelu_tanh
        from ..tensor.tensor import _run_op
        plan = fused_ffn_plan(x, (self.fc_in.weight,), self.fc_out.weight,
                              gelu_tanh, col_bias=self.fc_in.bias is not None)
        if plan is not None:
            # single island: fc_in matmul + bias + gelu stay on the mp shard,
            # fc_out rides the chunked reduce ring — no intermediate gather
            if self.fc_in.bias is not None:
                def f(a, w_in, b_in, w_out):
                    return plan(a, (w_in,), w_out, (b_in,))
                args = (x, self.fc_in.weight, self.fc_in.bias,
                        self.fc_out.weight)
            else:
                def f(a, w_in, w_out):
                    return plan(a, (w_in,), w_out)
                args = (x, self.fc_in.weight, self.fc_out.weight)
            out = _run_op("fused_ffn_overlap", f, args, {})
            if self.fc_out.bias is not None:
                out = out + self.fc_out.bias
            return self.dropout(out)
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x), approximate=True)))


class GPT2Block(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPT2Attention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPT2MLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPT2Model(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wpe = Embedding(config.max_position, config.hidden_size)
        self.drop = Dropout(config.dropout)
        self.h = LayerList([GPT2Block(config) for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = arange(s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)


class GPT2ForCausalLM(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.transformer = GPT2Model(config)
        self.config = config

    def forward(self, input_ids):
        h = self.transformer(input_ids)
        # tied lm head: project onto the (possibly vocab-sharded) embedding
        from ..tensor.linalg import matmul
        logits = matmul(h, self.transformer.wte.weight.T)
        return logits


def gpt2_loss(logits, labels):
    return F.cross_entropy(reshape(logits, [-1, logits.shape[-1]]),
                           reshape(labels, [-1]))


def build_gpt2_train_step(config: GPT2Config, mesh=None, lr=3e-4,
                          weight_decay=0.01):
    """Config-2 training: GPT-2 with TP=2 over the fleet mesh."""
    from jax.sharding import PartitionSpec as P
    from ..jit import TrainStep
    from ..optimizer import AdamW
    model = GPT2ForCausalLM(config)
    opt = AdamW(learning_rate=lr, parameters=model.parameters(),
                weight_decay=weight_decay)
    step = TrainStep(model, lambda out, lbl: gpt2_loss(out, lbl), opt,
                     mesh=mesh, batch_spec=P("dp") if mesh is not None else None)
    return model, opt, step


def gpt2_generate(model: GPT2ForCausalLM, input_ids, max_new_tokens=16,
                  top_k=1, temperature=1.0, seed=0):
    """Eager sampling loop (greedy when top_k=1) by re-forward per token —
    the dygraph-style demo path; the optimized single-dispatch KV-cache
    decode lives on the Llama flagship (models/llama.greedy_generate).
    Returns the generated continuation [B, max_new_tokens]."""
    import numpy as np

    from ..tensor.creation import to_tensor

    from ..autograd import no_grad

    rng = np.random.RandomState(seed)
    ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                     else input_ids)
    if ids.shape[1] + max_new_tokens > model.config.max_position:
        raise ValueError(
            f"generation would exceed max_position "
            f"({ids.shape[1]} + {max_new_tokens} > "
            f"{model.config.max_position}); the position-embedding gather "
            "would silently clamp beyond it")
    was_training = model.training
    model.eval()   # dropout off: greedy must be deterministic
    try:
        out = []
        with no_grad():   # no vjp tape for inference re-forwards
            for _ in range(max_new_tokens):
                logits = model(to_tensor(ids.astype(np.int64)))
                last = np.asarray(logits.numpy())[:, -1].astype(np.float64)
                if top_k <= 1:
                    nxt = last.argmax(-1)
                else:
                    k = min(top_k, last.shape[-1])
                    nxt = np.empty(last.shape[0], np.int64)
                    for b in range(last.shape[0]):
                        cand = (np.argpartition(-last[b], k - 1)[:k]
                                if k < last.shape[-1]
                                else np.arange(last.shape[-1]))
                        z = last[b, cand] / max(temperature, 1e-6)
                        p = np.exp(z - z.max())
                        p /= p.sum()
                        nxt[b] = rng.choice(cand, p=p)
                out.append(nxt)
                ids = np.concatenate([ids, nxt[:, None]], axis=1)
    finally:
        if was_training:
            model.train()
    return np.stack(out, axis=1)
