"""Llama model family — the flagship (BASELINE configs 3 & 4).

Ref: the reference trains Llama-2 via paddle.distributed.fleet HybridParallel
(ColumnParallelLinear/RowParallelLinear TP, PipelineLayer 1F1B, GroupSharded
ZeRO) + fused CUDA kernels (fused_rope, flash_attn, fused_rms_norm).

TPU-native architecture (not a translation):
- a PURE functional core: params are a pytree with every decoder layer
  STACKED on a leading axis, the depth loop is lax.scan (one compiled layer
  body), attention is the Pallas flash kernel, norms the fused RMSNorm,
  RoPE the fused rotary op. Remat per layer.
- parallelism is declarative: ParallelConfig(dp, mp, pp, sharding/fsdp, sep)
  maps to PartitionSpecs over the fleet mesh. TP/FSDP/DP via GSPMD param and
  activation specs; sep>1 switches attention to ring attention (KV rotation
  over ICI inside shard_map); pp>1 wraps the stage scan in the collective
  pipeline (shard_map over 'pp' + ppermute, see parallel/pipeline.py).
- the Layer-based eager API (LlamaForCausalLM) wraps the same functional
  core for dygraph-style use and weight interchange.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import shard_map
from ..observability import trace as _obs
from ..ops.flash_attention import flash_attention_bshd
from ..ops.rms_norm import fused_rms_norm
from ..ops.rope import apply_rope, build_rope_cache


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def llama_7b():
    return LlamaConfig()


def llama_13b():
    return LlamaConfig(hidden_size=5120, intermediate_size=13824,
                       num_hidden_layers=40, num_attention_heads=40,
                       num_key_value_heads=40)


def llama_tiny(vocab=256, hidden=64, layers=4, heads=4, kv_heads=2, inter=128,
               seq=128):
    return LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                       intermediate_size=inter, num_hidden_layers=layers,
                       num_attention_heads=heads, num_key_value_heads=kv_heads,
                       max_position_embeddings=seq, dtype=jnp.float32)


@dataclasses.dataclass
class ParallelConfig:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sharding: int = 1   # ZeRO/FSDP degree over the 'sharding' axis
    sep: int = 1        # context parallel (ring or ulysses attention)
    # context-parallel strategy: 'ring' (KV rotation) or 'ulysses'
    # (all-to-all heads<->sequence; needs num_heads % sep == 0).
    # None = follow PADDLE_TPU_SEP_STRATEGY (default 'ring').
    sep_strategy: Optional[str] = None
    microbatches: int = 1
    remat: bool = True
    # 'full' recomputes the whole block; 'dots' saves matmul outputs and
    # recomputes only cheap elementwise ops (jax checkpoint_policies) —
    # trades a little memory for most of the recompute FLOPs back.
    remat_policy: str = "full"
    # lax.scan unroll over the layer stack: >1 amortizes while-loop step
    # overhead (checkpoint granularity stays per-layer)
    scan_unroll: int = 1
    zero_stage: int = 3  # what 'sharding' shards: 1=os, 2=os+g, 3=os+g+p
    use_flash: Optional[bool] = None  # None = auto (TPU yes, CPU no)
    # async pp p2p: each activation ppermute overlaps the next tick's stage
    # compute (one extra skew tick per stage). None = PADDLE_TPU_PP_OVERLAP.
    overlap_p2p: Optional[bool] = None

    @property
    def total(self):
        return self.dp * self.mp * self.pp * self.sharding * self.sep


# ---------------------------------------------------------------------------
# parameter init
# ---------------------------------------------------------------------------

def init_llama_params(config: LlamaConfig, seed: int = 0) -> Dict[str, Any]:
    """Params with per-layer leaves stacked on axis 0 (length = num layers)."""
    c = config
    k = jax.random.PRNGKey(seed)
    keys = jax.random.split(k, 10)
    d = c.dtype
    h, kv = c.num_attention_heads, c.num_key_value_heads
    hd = c.head_dim
    std = 0.02

    def norm_init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(d)

    L = c.num_hidden_layers
    layers = {
        "input_norm": jnp.ones((L, c.hidden_size), d),
        "q_proj": norm_init(keys[1], (L, c.hidden_size, h * hd)),
        "k_proj": norm_init(keys[2], (L, c.hidden_size, kv * hd)),
        "v_proj": norm_init(keys[3], (L, c.hidden_size, kv * hd)),
        "o_proj": norm_init(keys[4], (L, h * hd, c.hidden_size)),
        "post_norm": jnp.ones((L, c.hidden_size), d),
        "gate_proj": norm_init(keys[5], (L, c.hidden_size, c.intermediate_size)),
        "up_proj": norm_init(keys[6], (L, c.hidden_size, c.intermediate_size)),
        "down_proj": norm_init(keys[7], (L, c.intermediate_size, c.hidden_size)),
    }
    params = {
        "embed": norm_init(keys[0], (c.vocab_size, c.hidden_size)),
        "layers": layers,
        "final_norm": jnp.ones((c.hidden_size,), d),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = norm_init(keys[8], (c.hidden_size, c.vocab_size))
    return params


def param_pspecs(config: LlamaConfig, parallel: ParallelConfig) -> Dict[str, Any]:
    """PartitionSpecs mirroring the reference's fleet sharding:
    column-parallel out-dim over 'mp', row-parallel in-dim over 'mp',
    FSDP shards a remaining big dim over 'sharding' (ZeRO-3)."""
    fs = "sharding" if (parallel.sharding > 1 and parallel.zero_stage >= 3) else None
    mp = "mp" if parallel.mp > 1 else None

    layers = {
        "input_norm": P(None, None),
        "q_proj": P(None, fs, mp),
        "k_proj": P(None, fs, mp),
        "v_proj": P(None, fs, mp),
        "o_proj": P(None, mp, fs),
        "post_norm": P(None, None),
        "gate_proj": P(None, fs, mp),
        "up_proj": P(None, fs, mp),
        "down_proj": P(None, mp, fs),
    }
    specs = {
        "embed": P(mp, fs),
        "layers": layers,
        "final_norm": P(None),
    }
    if not config.tie_word_embeddings:
        specs["lm_head"] = P(fs, mp)
    return specs


def opt_state_pspecs(config, parallel, pspec_tree):
    """ZeRO stage 1/2: optimizer states shard over 'sharding' even when the
    params don't. Stage >=3 states follow the (already sharded) param specs."""
    if parallel.sharding > 1 and parallel.zero_stage < 3:
        def shard_state(spec):
            parts = list(spec) if len(spec) else []
            for i, p_ in enumerate(parts):
                if p_ is None:
                    parts[i] = "sharding"
                    return P(*parts)
            return spec
        return jax.tree_util.tree_map(shard_state, pspec_tree,
                                      is_leaf=lambda x: isinstance(x, P))
    return pspec_tree


# ---------------------------------------------------------------------------
# functional forward
# ---------------------------------------------------------------------------

def _act_spec(parallel):
    # activations [B, S, H]: batch over dp(+sharding for ZeRO grads), seq over sep
    batch_axes = ("dp",) if parallel.sharding == 1 else ("dp", "sharding")
    seq_axis = "sep" if parallel.sep > 1 else None
    return P(batch_axes, seq_axis, None)


def _maybe_hint(x, mesh, spec):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))



def _mat(x, w):
    """x @ w for plain weights, weight-only int8 ({'w': int8 [..., in,
    out], 's': [..., out] scales}), or the decode-transposed form (key
    'wT': [..., out, in], optional 's'; see _decode_weights). The int8->bf16
    convert fuses into the matmul's operand read (measured 1.97x on a
    decode-shaped matvec), so quantized weights stream at half the
    bytes — see quantize_llama_int8."""
    if isinstance(w, dict):
        if "wT" in w:
            r = jnp.einsum("...i,oi->...o", x, w["wT"].astype(x.dtype))
            return r * w["s"].astype(x.dtype) if "s" in w else r
        return (x @ w["w"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def _mat_out_dim(w):
    if isinstance(w, dict):
        if "wT" in w:
            return w["wT"].shape[-2]
        return w["w"].shape[-1]
    return w.shape[-1]


def _decode_weights(params, config):
    """Transpose the stacked q/k/v projections to [L, out, in] ONCE per
    generate call (outside the token scan). XLA's chosen operand layout
    for the [B, H] @ W decode matmuls is in-dim-minor; slicing the
    natural [L, in, out] stack per layer forced a 2 MB relayout copy per
    projection per layer EVERY token step (profiled ~0.2 ms/step at hd64
    b8 — constant_dynamic-slice fusions with transposed output layout).
    The transposed stack slices straight into the wanted layout; the
    one-time transpose cost amortizes over the whole continuation.

    HBM note (advisor r4): when this runs INSIDE a generate/sample jit
    the raw q/k/v stacks remain live as jit inputs while the fused copy
    exists, so decode holds ~2x the qkv projection bytes (GB-scale at
    7B+). Callers decoding repeatedly should pre-prepare once with
    prepare_decode_params (donating the raw stacks) instead."""
    layers = dict(params["layers"])
    if "qkv_proj" in layers:
        return params  # already prepared
    # the fused split site (llama_decode_step) re-derives nh/nkv from
    # config, so only fuse when the actual weight shapes agree with the
    # config's head ratio — mismatched params (e.g. pruned heads) keep
    # the unfused three-matmul path instead of silently mis-splitting
    q_out = _mat_out_dim(layers["q_proj"])
    k_out = _mat_out_dim(layers["k_proj"])
    ratio = config.num_attention_heads // config.num_key_value_heads
    if q_out != k_out * ratio or k_out != _mat_out_dim(layers["v_proj"]):
        for name in ("q_proj", "k_proj", "v_proj"):
            w = layers[name]
            if isinstance(w, dict):
                if "wT" in w:
                    continue
                layers[name] = {"wT": jnp.swapaxes(w["w"], -1, -2),
                                "s": w["s"]}
            else:
                layers[name] = {"wT": jnp.swapaxes(w, -1, -2)}
        out = dict(params)
        out["layers"] = layers
        return out
    ws = [layers.pop(n) for n in ("q_proj", "k_proj", "v_proj")]
    if isinstance(ws[0], dict):
        layers["qkv_proj"] = {
            "wT": jnp.concatenate(
                [jnp.swapaxes(w["w"], -1, -2) for w in ws], axis=-2),
            "s": jnp.concatenate([w["s"] for w in ws], axis=-1),
        }
    else:
        layers["qkv_proj"] = {"wT": jnp.concatenate(
            [jnp.swapaxes(w, -1, -2) for w in ws], axis=-2)}
    out = dict(params)
    out["layers"] = layers
    return out


def prepare_decode_params(params, config):
    """Pre-fuse/transpose the q/k/v projection stacks for decode ONCE,
    outside any generate call, DONATING the raw stacks. generate_scan/
    sample_scan re-derive the fused copy internally when handed raw
    training-layout params, and since the raw stacks stay live as jit
    inputs, decode then holds ~2x the qkv projection bytes in HBM
    (advisor r4). After ``params = prepare_decode_params(params, cfg)``
    only the fused copy is resident (pass-through weights alias via
    donation), and every subsequent generate call skips the re-derive.
    Idempotent: prepared params return unchanged (both the fused
    qkv_proj form and the unfused wT form that shape-mismatched — e.g.
    pruned-head — params take)."""
    layers = params["layers"]
    if "qkv_proj" in layers or (
            isinstance(layers.get("q_proj"), dict)
            and "wT" in layers["q_proj"]):
        return params
    fn = jax.jit(lambda p: _decode_weights(p, config), donate_argnums=(0,))
    return fn(params)


def quantize_llama_int8(params):
    """Weight-only int8 quantization for serving (ref: the reference's
    weight-only path in paddle.quantization + its int8 fused kernels).

    Matmul weights become {'w': int8, 's': per-output-channel bf16 scale}
    (symmetric, per (layer, out) channel for the stacked layer weights);
    the embedding (row gather, never streamed) and norms keep their float
    dtype. Decode is weight-stream-bound, so halving the bytes roughly
    doubles decode throughput — BELOW the bf16 weight floor, which is the
    point. Training/prefill accuracy paths should keep the float params."""
    names = {"q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
             "up_proj", "down_proj"}

    def quant(w):
        from ..nn.quant import absmax_intq
        wi, sc = absmax_intq(w, axis=-2)
        return {"w": wi, "s": jnp.squeeze(sc, -2).astype(w.dtype)}

    out = dict(params)
    out["layers"] = {k: (quant(v) if k in names else v)
                     for k, v in params["layers"].items()}
    if "lm_head" in params:
        out["lm_head"] = quant(params["lm_head"])
    return out


def decoder_layer(p, h_in, cos, sin, config: LlamaConfig,
                  parallel: ParallelConfig, mesh=None, use_flash=True,
                  in_shard_map=False, tp_axis=None):
    """One decoder block. h_in: [B, S, H].

    tp_axis: when set (inside a manual shard_map region) weights arrive
    mp-SLICED and this runs the explicit Megatron pattern — local head slice
    compute + lax.psum after the row-parallel matmuls (o_proj, down_proj);
    when None, GSPMD derives the same collectives from param shardings.
    """
    from jax.ad_checkpoint import checkpoint_name as _ckpt_name
    c = config
    b, s, _ = h_in.shape
    hd = c.head_dim
    nh = _mat_out_dim(p["q_proj"]) // hd  # local head count (sliced under TP)
    nkv = _mat_out_dim(p["k_proj"]) // hd

    # jax.named_scope boundaries (measurement-only): the scope names land
    # in the lowered ops' metadata, so device traces and merge_device_trace
    # can attribute kernel time back to step components by name.
    with jax.named_scope("decoder.qkv"):
        x = fused_rms_norm(h_in, p["input_norm"], c.rms_norm_eps)
        q = _mat(x, p["q_proj"]).reshape(b, s, nh, hd)
        k = _mat(x, p["k_proj"]).reshape(b, s, nkv, hd)
        v = _mat(x, p["v_proj"]).reshape(b, s, nkv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

    with jax.named_scope("decoder.attn"):
        if parallel.sep > 1 and in_shard_map:
            from ..parallel.ring_attention import ring_attention
            from ..parallel.ulysses_attention import (resolve_sep_strategy,
                                                      ulysses_attention)
            if resolve_sep_strategy(parallel.sep_strategy) == "ulysses":
                if use_flash:
                    attn = ulysses_attention(q, k, v, axis_name="sep",
                                             causal=True)
                else:
                    from ..nn.functional.attention import _xla_sdpa
                    attn = ulysses_attention(
                        q, k, v, axis_name="sep", causal=True,
                        attn_fn=lambda qg, kg, vg: _xla_sdpa(
                            qg, kg, vg, is_causal=True))
            else:
                attn = ring_attention(q, k, v, axis_name="sep", causal=True,
                                      impl="flash" if use_flash else "xla")
        elif use_flash:
            attn = flash_attention_bshd(q, k, v, causal=True)
        else:
            from ..nn.functional.attention import _xla_sdpa
            attn = _xla_sdpa(q, k, v, is_causal=True)
        attn = attn.reshape(b, s, nh * hd)
        # named so the 'save_attn' remat policy can keep it (skips
        # recomputing the flash kernel in backward at the cost of one
        # [B,S,H*D] residual)
        attn = _ckpt_name(attn, "attn_out")
        attn_out = _mat(attn, p["o_proj"])
        if tp_axis is not None:
            attn_out = lax.psum(attn_out, tp_axis)
    h = h_in + _maybe_hint(attn_out, mesh, _act_spec(parallel))

    with jax.named_scope("decoder.ffn"):
        x = fused_rms_norm(h, p["post_norm"], c.rms_norm_eps)
        mlp_out = _fused_ffn_overlap(x, p, parallel, mesh, tp_axis)
        if mlp_out is None:
            # named so 'save_mlp' can keep the gate/up matmul outputs across
            # the remat boundary — gate+up are HALF the forward matmul
            # FLOPs, so saving them halves the backward recompute at the
            # cost of two [B, S, I] residuals per layer
            g = _ckpt_name(_mat(x, p["gate_proj"]), "mlp_gate")
            u = _ckpt_name(_mat(x, p["up_proj"]), "mlp_up")
            gated = jax.nn.silu(g) * u
            mlp_out = _mat(gated, p["down_proj"])
            if tp_axis is not None:
                mlp_out = lax.psum(mlp_out, tp_axis)
    out = h + _maybe_hint(mlp_out, mesh, _act_spec(parallel))
    return out


def _fused_ffn_overlap(x, p, parallel, mesh, tp_axis):
    """gate/up -> silu-mul -> down inside ONE ring island (the [B, S, I]
    activation never leaves the mp shard; the only collective is the down
    matmul's chunked reduce ring). None -> run the GSPMD path: overlap off,
    manual-TP region (weights arrive pre-sliced), sep sharding on the seq
    dim, 'save_mlp' remat (the island hides the gate/up checkpoint names),
    int8 weights, or shapes that don't divide the ring."""
    from ..parallel import collective_matmul as cm
    if (tp_axis is not None or mesh is None or parallel.mp <= 1
            or parallel.sep > 1 or parallel.remat_policy == "save_mlp"
            or not cm.overlap_enabled()
            or any(isinstance(p[k], dict)
                   for k in ("gate_proj", "up_proj", "down_proj"))):
        return None
    plan = cm.plan_fused_ffn(
        tuple(x.shape), tuple(p["gate_proj"].shape),
        tuple(p["down_proj"].shape), mesh, n_cols=2, activation=cm.swiglu,
        batch_axis=_act_spec(parallel)[0])
    if plan is None:
        return None
    return plan(x, (p["gate_proj"], p["up_proj"]), p["down_proj"])


def _remat_policy(parallel):
    """Resolve ParallelConfig.remat_policy to a jax checkpoint policy."""
    if parallel.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if parallel.remat_policy == "save_attn":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if parallel.remat_policy == "save_mlp":
        # attn output + gate/up matmul outputs: backward recomputes only
        # the cheap elementwise/norm chain plus qkv/o (19% of fwd FLOPs)
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "mlp_gate", "mlp_up")
    if parallel.remat_policy == "full":
        return None
    if parallel.remat_policy == "offload_attn":
        # keep flash outputs across the remat boundary but park them in
        # host RAM instead of HBM: frees activation memory for larger
        # batch/depth at big hidden sizes (the v5e HBM ceiling binds
        # before compute does at 7B-layer geometry)
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["attn_out"],
            offload_src="device", offload_dst="pinned_host")
    raise ValueError(
        f"unknown remat_policy {parallel.remat_policy!r}; "
        "expected 'full', 'dots', 'save_attn', 'save_mlp', or "
        "'offload_attn'")


def vocab_parallel_embed(embed, ids, config, parallel, mesh=None,
                         force_matmul=False):
    """Embedding lookup that PARTITIONS when the table is vocab-sharded.

    A plain jnp.take over an embed table sharded P('mp', ...) is a gather
    GSPMD cannot partition: the compiler emits "Involuntary full
    rematerialization" and all-gathers the whole [V, H] table every step
    (recorded in MULTICHIP_r04). This is exactly what the reference's
    VocabParallelEmbedding avoids (ref: fleet/meta_parallel/
    parallel_layers/mp_layers.py): each mp shard looks up only ids that
    land in its vocab slice (masked local gather) and the partial rows
    are summed over 'mp' — every (b, s) row is non-zero on exactly one
    shard, so the psum is exact in any dtype. Implemented as a partial-
    manual shard_map over 'mp' alone; dp/sharding/sep stay auto."""
    c = config
    mp_sharded = (mesh is not None and parallel.mp > 1
                  and "mp" in mesh.axis_names)
    if not (mp_sharded or force_matmul):
        return jnp.take(embed, ids, axis=0).astype(c.dtype)
    # One-hot matmul: the lookup becomes [B,S,V] @ [V,H] with the vocab
    # dim CONTRACTED — GSPMD partitions it over 'mp' as local partial
    # products + psum (each shard multiplies only its vocab slice:
    # numerically the reference's masked-local-lookup + allreduce), and
    # the hidden dim over 'sharding' falls out of normal matmul
    # partitioning. The backward is the transposed matmul — equally
    # partition-friendly, unlike take's scatter-add whose cotangent
    # resharding was r4's second involuntary-remat warning. XLA fuses
    # the iota/compare one-hot into the dot's operand read, so the
    # [B,S,V] operand never materializes in HBM.
    oh = jax.nn.one_hot(ids, embed.shape[0], dtype=embed.dtype)
    return jnp.einsum("bsv,vh->bsh", oh, embed).astype(c.dtype)


def llama_hidden(params, ids, config, parallel, mesh=None, use_flash=True,
                 layer_slice=None, in_shard_map=False):
    """Embed + scan decoder stack. Returns final hidden (pre-norm)."""
    c = config
    # inside the sep manual region the mesh handle is gone but the table
    # is still mp-sharded on the auto axes — keep the one-hot matmul
    # there too (the in-region take is the same unpartitionable gather)
    h = vocab_parallel_embed(params["embed"], ids, config, parallel,
                             None if in_shard_map else mesh,
                             force_matmul=in_shard_map and parallel.mp > 1)
    h = _maybe_hint(h, mesh, _act_spec(parallel))
    s_total = ids.shape[1] * (parallel.sep if in_shard_map else 1)
    cos, sin = build_rope_cache(s_total, c.head_dim, base=c.rope_theta)
    if parallel.sep > 1 and in_shard_map:
        # each sep shard sees its slice of positions
        idx = lax.axis_index("sep") * ids.shape[1]
        cos = lax.dynamic_slice_in_dim(cos, idx, ids.shape[1], 0)
        sin = lax.dynamic_slice_in_dim(sin, idx, ids.shape[1], 0)

    body = functools.partial(decoder_layer, config=c, parallel=parallel,
                             mesh=mesh, use_flash=use_flash,
                             in_shard_map=in_shard_map)
    raw_body = lambda h, p: (body(p, h, cos, sin), None)
    if parallel.remat:
        scan_body = jax.checkpoint(raw_body, policy=_remat_policy(parallel))
    else:
        scan_body = raw_body
    layer_params = params["layers"]
    if layer_slice is not None:
        layer_params = jax.tree_util.tree_map(lambda a: a[layer_slice],
                                              layer_params)
    h, _ = lax.scan(scan_body, h, layer_params,
                    unroll=parallel.scan_unroll)
    return h


def llama_logits(params, h, config):
    with jax.named_scope("lm_head"):
        x = fused_rms_norm(h, params["final_norm"], config.rms_norm_eps)
        if config.tie_word_embeddings:
            return x @ params["embed"].T
        return _mat(x, params["lm_head"])


def masked_ce_loss(logits, labels, sep_psum: bool = False, psum_axes=None):
    """Mean CE over labels != -100 (fp32 logits). With sep_psum (or an
    explicit psum_axes tuple of MANUAL mesh axes), the sum and the token
    count are psum'd over those axes BEFORE the clamp so shards with no
    valid tokens don't deflate the denominator."""
    if psum_axes is None and sep_psum:
        psum_axes = ("sep",)
    with jax.named_scope("ce_loss"):
        mask = labels != -100
        safe = jnp.where(mask, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss_sum = jnp.sum(jnp.where(mask, -picked, 0.0))
        count = jnp.sum(mask)
        if psum_axes:
            loss_sum = lax.psum(loss_sum, psum_axes)
            count = lax.psum(count, psum_axes)
        return loss_sum / jnp.maximum(count, 1)


def chunked_ce_loss(x, head, labels, sep_psum: bool = False, n_chunks=8):
    """Fused head-matmul + CE over SEQUENCE chunks: the full [B*S, vocab]
    fp32 logits (1 GB at the flagship shape) never materialize — each
    chunk's logits live once for (lse, picked) and are rematerialized for
    the backward (jax.checkpoint), trading one extra chunk matmul for
    several HBM round-trips of the big array (~8 ms/step measured on v5e).
    Chunking the sequence axis (not flattened B*S) keeps the batch dim
    intact for GSPMD dp sharding. x: [B, S, D]; head: [D, vocab]."""
    b, s, d = x.shape
    rem = (-s) % n_chunks
    if rem:
        # pad to a chunk multiple with ignored labels — falling back to
        # dense would materialize exactly the logits this function avoids
        x = jnp.pad(x, ((0, 0), (0, rem), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, rem)), constant_values=-100)
        s += rem

    @jax.checkpoint
    def chunk(xc, lc):
        logits = (xc @ head).astype(jnp.float32)
        m = lc != -100
        safe = jnp.where(m, lc, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return (jnp.sum(jnp.where(m, lse - picked, 0.0)),
                m.sum().astype(jnp.float32))

    xt = x.reshape(b, n_chunks, s // n_chunks, d).swapaxes(0, 1)
    lt = labels.reshape(b, n_chunks, s // n_chunks).swapaxes(0, 1)

    def body(c, xs):
        ls, cnt = chunk(*xs)
        return (c[0] + ls, c[1] + cnt), None

    (ls, cnt), _ = lax.scan(body, (jnp.float32(0.0), jnp.float32(0.0)),
                            (xt, lt))
    if sep_psum:
        ls = lax.psum(ls, "sep")
        cnt = lax.psum(cnt, "sep")
    return ls / jnp.maximum(cnt, 1.0)


def llama_loss(params, ids, labels, config, parallel=ParallelConfig(),
               mesh=None, use_flash=True, in_shard_map=False,
               loss_psum_axes=None):
    """Causal LM loss, fp32 softmax. labels: [B, S] with -100 = ignore.

    Uses the DENSE logits path: chunked_ce_loss measured faster in
    isolation (~8 ms) but SLOWER composed into the full train step
    (+14 ms — the sequential per-chunk head-grad matmuls lose more MXU
    efficiency than the saved logits traffic); kept available for
    memory-constrained callers."""
    h = llama_hidden(params, ids, config, parallel, mesh, use_flash,
                     in_shard_map=in_shard_map)
    logits = llama_logits(params, h, config).astype(jnp.float32)
    # psum over whatever MANUAL axes shard the loss terms (callers pass
    # loss_psum_axes; default: 'sep' alone — dp/sharding stay auto and
    # GSPMD reduces them)
    return masked_ce_loss(
        logits, labels,
        psum_axes=(loss_psum_axes if loss_psum_axes is not None
                   else (("sep",) if in_shard_map and parallel.sep > 1
                         else ())))


# ---------------------------------------------------------------------------
# KV-cache decode (ref: fused_multi_transformer_op.cu — the reference's
# inference kernel is a full decoder stack with an in-place KV cache)
# ---------------------------------------------------------------------------

def init_kv_cache(config: LlamaConfig, batch: int, max_len: int):
    """Stacked per-layer cache: k AND v [L, B, KV*HD, max_len]
    (time-in-lanes slabs) for head_dim < 128.

    These are the layouts the BLOCK-DIAGONAL decode attention consumes
    (see llama_decode_step): scores = Q_blockdiag [NH, KV*HD] @ K-slab
    [KV*HD, T] and values = V-slab [KV*HD, T] contracted over T — one
    MXU-shaped matmul per batch element per layer instead of NH separate
    [1, HD] matvecs. At head_dim 64 the per-head matvecs ran 2.5x their
    bytes-bound time (M=1 sublane padding + HD=64 half-lane contraction,
    profiled 14 us vs 5.6 for the score einsum at b8); the slab matmuls
    are bytes-bound. V shares K's layout so both per-token writes are
    in-place lane columns and both per-layer reads fuse into the dot —
    a time-major [T, KV*HD] V measured a 4.2 MB slice copy + a copying
    row update per layer per step (~0.26 ms/step at hd64 b8). At
    head_dim >= 128 the per-head contraction already fills the lanes and
    the block-diag detour measured SLOWER (flagship b8: 2.92 vs 2.81
    ms/step), so those configs keep the head-major [L, B, KV, T, HD]
    cache + grouped einsums. Earlier layouts for the next reader:
    [B, T, KV, HD] forced whole-cache transposes every layer (~1.5
    ms/step of pure copies)."""
    c = config
    if c.head_dim >= 128:
        shape = (c.num_hidden_layers, batch, c.num_key_value_heads,
                 max_len, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype),
                "v": jnp.zeros(shape, c.dtype),
                "pos": jnp.zeros((), jnp.int32)}
    kvd = c.num_key_value_heads * c.head_dim
    shape = (c.num_hidden_layers, batch, kvd, max_len)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def llama_prefill(params, cache, ids, config: LlamaConfig):
    """Batched prompt prefill: one pass over [B, S] fills the KV cache and
    returns last-position logits — S single-token decode dispatches collapse
    into one compiled call with MXU-sized matmuls."""
    c = config
    b, s = ids.shape
    slab = c.head_dim < 128  # see init_kv_cache
    max_len = cache["k"].shape[3]  # T is dim 3 in both layouts
    h = jnp.take(params["embed"], ids, axis=0).astype(c.dtype)  # [B, S, H]
    cos_all, sin_all = build_rope_cache(max_len, c.head_dim, base=c.rope_theta)
    cos, sin = cos_all[:s], sin_all[:s]

    def layer_step(h, xs):
        p, k_cache, v_cache = xs
        hd = c.head_dim
        x = fused_rms_norm(h, p["input_norm"], c.rms_norm_eps)
        if "qkv_proj" in p:
            # decode-prepared params (prepare_decode_params): one fused
            # matmul, split into q/k/v
            ratio = c.num_attention_heads // c.num_key_value_heads
            nkv = _mat_out_dim(p["qkv_proj"]) // hd // (ratio + 2)
            nh = nkv * ratio
            qkv = _mat(x, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(b, s, nh, hd)
            k = k.reshape(b, s, nkv, hd)
            v = v.reshape(b, s, nkv, hd)
        else:
            nh = _mat_out_dim(p["q_proj"]) // hd
            nkv = _mat_out_dim(p["k_proj"]) // hd
            q = _mat(x, p["q_proj"]).reshape(b, s, nh, hd)
            k = _mat(x, p["k_proj"]).reshape(b, s, nkv, hd)
            v = _mat(x, p["v_proj"]).reshape(b, s, nkv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if slab:
            # k and v [B, KV*HD, T] (time-in-lanes)
            k_cache = lax.dynamic_update_slice(
                k_cache,
                k.reshape(b, s, nkv * hd).transpose(0, 2, 1)
                 .astype(k_cache.dtype),
                (0, 0, 0))
            v_cache = lax.dynamic_update_slice(
                v_cache,
                v.reshape(b, s, nkv * hd).transpose(0, 2, 1)
                 .astype(v_cache.dtype),
                (0, 0, 0))
        else:
            # head-major [B, KV, T, HD]
            k_cache = lax.dynamic_update_slice(
                k_cache, k.transpose(0, 2, 1, 3).astype(k_cache.dtype),
                (0, 0, 0, 0))
            v_cache = lax.dynamic_update_slice(
                v_cache, v.transpose(0, 2, 1, 3).astype(v_cache.dtype),
                (0, 0, 0, 0))
        from ..ops._common import interpret_mode
        if s >= 1024 and not interpret_mode():
            # long prompts: the Pallas flash kernel (O(S) memory, causal
            # DMA skipping) — XLA sdpa materializes [B, H, S, S] scores
            attn = flash_attention_bshd(q, k, v, causal=True)
        else:
            from ..nn.functional.attention import _xla_sdpa
            attn = _xla_sdpa(q, k, v, is_causal=True)
        attn_out = _mat(attn.reshape(b, s, nh * hd), p["o_proj"])
        h = h + attn_out
        x2 = fused_rms_norm(h, p["post_norm"], c.rms_norm_eps)
        gated = jax.nn.silu(_mat(x2, p["gate_proj"])) * _mat(x2, p["up_proj"])
        h = h + _mat(gated, p["down_proj"])
        return h, (k_cache, v_cache)

    h, (new_k, new_v) = lax.scan(layer_step, h,
                                 (params["layers"], cache["k"], cache["v"]))
    logits = llama_logits(params, h[:, -1:], config)[:, 0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v,
                                        "pos": cache["pos"] + s}


def llama_decode_step(params, cache, ids, config: LlamaConfig):
    """One incremental decode step: ids [B, 1] -> (logits [B, vocab], cache).

    jit-stable: cache position is a traced scalar, cache updates are
    dynamic_update_slice, attention masks positions >= pos+1. The layer
    loop is a lax.scan over the stacked layer params + cache slices
    (measured: an unrolled static-index loop is SLOWER at b8 — the scan's
    per-iteration xs slicing pipelines the weight stream better than a
    chain of static slices, 2.57 vs 2.15 ms/step on the hd64 shape).
    """
    c = config
    b = ids.shape[0]
    slab = c.head_dim < 128  # see init_kv_cache
    max_len = cache["k"].shape[3]  # T is dim 3 in both layouts
    pos = cache["pos"]
    h = jnp.take(params["embed"], ids[:, 0], axis=0).astype(c.dtype)  # [B, H]

    cos_all, sin_all = build_rope_cache(max_len, c.head_dim,
                                        base=c.rope_theta)
    cos = lax.dynamic_slice_in_dim(cos_all, pos, 1, 0)
    sin = lax.dynamic_slice_in_dim(sin_all, pos, 1, 0)

    def layer_step(carry, xs):
        # full stacked caches ride the CARRY (in-place loop state, buffer
        # aliased across iterations), NOT xs/ys: a ys cache would be
        # copied wholesale every layer of every token (~full-cache HBM
        # traffic per step — measured 2.5x decode slowdown at b8)
        h, kc, vc = carry
        p, layer = xs
        hd = c.head_dim
        x = fused_rms_norm(h[:, None], p["input_norm"], c.rms_norm_eps)
        if "qkv_proj" in p:
            # fused projection (_decode_weights): one weight slice + one
            # matmul per layer instead of three
            ratio = c.num_attention_heads // c.num_key_value_heads
            nkv = _mat_out_dim(p["qkv_proj"]) // hd // (ratio + 2)
            nh = nkv * ratio
            qkv = _mat(x, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(b, 1, nh, hd)
            k = k.reshape(b, 1, nkv, hd)
            v = v.reshape(b, 1, nkv, hd)
        else:
            nh = _mat_out_dim(p["q_proj"]) // hd
            nkv = _mat_out_dim(p["k_proj"]) // hd
            q = _mat(x, p["q_proj"]).reshape(b, 1, nh, hd)
            k = _mat(x, p["k_proj"]).reshape(b, 1, nkv, hd)
            v = _mat(x, p["v_proj"]).reshape(b, 1, nkv, hd)
        kvd = nkv * hd
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        zero = jnp.zeros((), jnp.int32)
        layer_i = jnp.asarray(layer, jnp.int32)
        rep = nh // nkv
        qg = q[:, 0].reshape(b, nkv, rep, hd)
        if slab:
            # BLOCK-DIAGONAL attention: per batch element ONE [NH, KV*HD]
            # x [KV*HD, T] score matmul and ONE [KV*HD, T] x [T, NH]
            # value matmul. q is scattered into a block-diagonal
            # [NH, KV*HD] (head (g, r) occupies kv-group g's column
            # block; the zeros kill cross-head terms exactly), and the
            # value result's diagonal blocks are gathered back. Trades
            # nkv x padded FLOPs (~0.3 us/layer; decode is bytes-bound)
            # for MXU-shaped operands: per-head [1, HD<128] matvecs ran
            # 2.5x bytes-bound time (M=1 sublane padding, profiled 14 vs
            # 5.6 us at hd64 b8); a VPU broadcast+reduce formulation was
            # worse still (2.48 ms/step).
            eye = jnp.eye(nkv, dtype=qg.dtype)
            q_bd = jnp.einsum("bgrd,ge->bgred", qg, eye).reshape(b, nh, kvd)
            if max_len % 128 == 0:
                # fused Pallas attend+update: the new k/v column is
                # written in-place INSIDE the kernel (caches alias
                # through the custom call), and the attention reads the
                # slabs directly — neither the per-layer cache slice
                # nor the V relayout copy exists. Requires the
                # 128-aligned cache extents _prefill_for_generate now
                # allocates.
                from ..ops.decode_attention import (
                    _LOG2E, decode_attend_update_slab)
                qs = (q_bd.astype(jnp.float32)
                      * (_LOG2E / (hd ** 0.5))).astype(q_bd.dtype)
                attn_full, kc, vc = decode_attend_update_slab(
                    qs, k.reshape(b, kvd).astype(kc.dtype),
                    v.reshape(b, kvd).astype(vc.dtype), kc, vc,
                    layer_i, pos)
            else:
                # ragged extent: XLA einsum path. V slab as the dot RHS
                # contracting its minor (T) dim — the same operand role
                # the K slab plays in the score einsum, so XLA assigns
                # the same in-place layout.
                kc = lax.dynamic_update_slice(
                    kc, k.reshape(b, kvd, 1).astype(kc.dtype)[None],
                    (layer_i, zero, zero, pos))
                vc = lax.dynamic_update_slice(
                    vc, v.reshape(b, kvd, 1).astype(vc.dtype)[None],
                    (layer_i, zero, zero, pos))
                k_cache = lax.dynamic_index_in_dim(kc, layer, 0,
                                                   keepdims=False)
                v_cache = lax.dynamic_index_in_dim(vc, layer, 0,
                                                   keepdims=False)
                scores = jnp.einsum("bhc,bct->bht", q_bd, k_cache,
                                    preferred_element_type=jnp.float32)
                scores = scores / (hd ** 0.5)
                valid = jnp.arange(max_len)[None, None, :] <= pos
                scores = jnp.where(valid, scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1) \
                    .astype(v_cache.dtype)
                attn_full = jnp.einsum("bht,bct->bhc", probs, v_cache,
                                       preferred_element_type=jnp.float32)
            attn = jnp.einsum("bgred,ge->bgrd",
                              attn_full.reshape(b, nkv, rep, nkv, hd),
                              eye.astype(attn_full.dtype)).astype(c.dtype)
        else:
            # head-major cache [B, KV, T, HD]: grouped-query einsums
            # against contiguous per-head [T, HD] panels — at HD >= 128
            # the contraction fills the lanes and this is bytes-bound;
            # the block-diag detour measured slower here.
            kc = lax.dynamic_update_slice(
                kc, k.transpose(0, 2, 1, 3).astype(kc.dtype)[None],
                (layer_i, zero, zero, pos, zero))
            vc = lax.dynamic_update_slice(
                vc, v.transpose(0, 2, 1, 3).astype(vc.dtype)[None],
                (layer_i, zero, zero, pos, zero))
            k_cache = lax.dynamic_index_in_dim(kc, layer, 0, keepdims=False)
            v_cache = lax.dynamic_index_in_dim(vc, layer, 0, keepdims=False)
            scores = jnp.einsum("bgrd,bgtd->bgrt", qg, k_cache,
                                preferred_element_type=jnp.float32)
            scores = scores / (hd ** 0.5)
            valid = jnp.arange(max_len)[None, None, None, :] <= pos
            scores = jnp.where(valid, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
            attn = jnp.einsum("bgrt,bgtd->bgrd", probs, v_cache,
                              preferred_element_type=jnp.float32
                              ).astype(c.dtype)
        attn_out = _mat(attn.reshape(b, nh * hd), p["o_proj"])
        h = h + attn_out

        x2 = fused_rms_norm(h[:, None], p["post_norm"], c.rms_norm_eps)[:, 0]
        gated = jax.nn.silu(_mat(x2, p["gate_proj"])) * _mat(x2, p["up_proj"])
        h = h + _mat(gated, p["down_proj"])
        return (h, kc, vc), None

    n_layers = cache["k"].shape[0]
    (h, new_k, new_v), _ = lax.scan(
        layer_step, (h, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)))
    logits = llama_logits(params, h[:, None], config)[:, 0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v,
                                        "pos": pos + 1}


def init_paged_kv_pool(config: LlamaConfig, num_blocks: int,
                       block_size: int, kv_dtype: str = "auto"):
    """Paged KV pool for the serving engine: k and v
    [L, num_blocks, KV*HD, block_size] — each block is a time-in-lanes
    slab fragment, so the paged kernel's per-block dots are the same
    [KVD, bs] shapes the contiguous slab kernel tiles into. Block 0 is
    reserved as the null block (see inference/kv_cache.py): padding
    rows scribble there and live tables never reference it.

    ``kv_dtype='auto'`` stores the model dtype (the pre-PR-16 path,
    bit-identical); ``'int8'`` stores quantized bytes — pair with
    :func:`init_paged_kv_scales`."""
    c = config
    if kv_dtype not in ("auto", "int8"):
        raise ValueError(f"kv_dtype must be 'auto' or 'int8', "
                         f"got {kv_dtype!r}")
    dt = jnp.int8 if kv_dtype == "int8" else c.dtype
    kvd = c.num_key_value_heads * c.head_dim
    shape = (c.num_hidden_layers, num_blocks, kvd, block_size)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def init_paged_kv_scales(config: LlamaConfig, num_blocks: int,
                         block_size: int):
    """f32 scale pools [L, num_blocks, NKV, block_size] for an int8
    paged KV pool: one symmetric absmax scale per block / kv head /
    COLUMN (ops/paged_attention.kv_quant_columns). Zero-initialized so
    never-written columns (incl. null-block scribbles) dequantize to
    exactly 0."""
    c = config
    shape = (c.num_hidden_layers, num_blocks, c.num_key_value_heads,
             block_size)
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


# ---------------------------------------------------------------------------
# tensor-parallel serving (PR 19): paged steps inside an mp shard_map
# ---------------------------------------------------------------------------
#
# The serving TP path threads ``tp=(axis_name, n)`` through the three
# paged step functions below. Weights arrive PRE-SLICED by the island's
# in_specs (param_pspecs over 'mp'), so the column-parallel projections
# need no code change at all — nh/nkv are derived from weight shapes and
# become local head counts, the paged kernels and their _fit_* fitters
# price the per-shard [KVD/n, bs] geometry from argument shapes, and the
# block-diagonal-q attention is exact per kv-head. Only three collectives
# exist: the vocab-parallel embed psum (exact — each id is non-zero on
# one rank), the o_proj/down_proj row-parallel reduce (the ONLY
# re-associated sums vs mp=1; greedy argmax keeps token streams
# identical), and the verify logits all-gather (exact vocab concat so
# accept/commit logic is rank-identical). See PARITY.md (PR 19).

def _tp_vocab_embed(embed, ids, tp):
    """Masked vocab-parallel lookup INSIDE the serving island: ``embed``
    is this rank's [V/n, H] vocab slice; every id row is non-zero on
    exactly one rank, so the psum is EXACT in any dtype (same contract
    as vocab_parallel_embed, manual-collective form)."""
    axis, _ = tp
    vs = embed.shape[0]
    local = ids - lax.axis_index(axis) * vs
    ok = (local >= 0) & (local < vs)
    rows = jnp.take(embed, jnp.clip(local, 0, vs - 1), axis=0)
    rows = jnp.where(ok[..., None], rows, 0).astype(embed.dtype)
    with _obs.comm_span("serve.tp_ring.embed",
                        nbytes=rows.size * rows.dtype.itemsize,
                        site="serve.tp_ring.embed"):
        return lax.psum(rows, axis)


def _tp_row_matmul(x, w, tp):
    """Row-parallel ``x @ w_local`` + cross-rank reduce for the serving
    TP path: x [..., k/n] holds this rank's slice of the contracted dim
    (its attention heads / FFN columns), w [k/n, out] the matching row
    shard. Routes through the overlapped reduce-scatter ring
    (ring_allreduce_matmul) when PADDLE_TPU_TP_OVERLAP is on and the
    row count divides the ring, else the blocking psum — the mp=2 ring
    is pinned bitwise-vs-blocking (parallel/collective_matmul), so the
    knob never changes mp=2 streams."""
    from ..parallel.collective_matmul import (overlap_enabled,
                                              resolve_chunks,
                                              ring_allreduce_matmul)
    axis, n = tp
    lead = x.shape[:-1]
    t = x.size // x.shape[-1]
    x2 = x.reshape(t, x.shape[-1])
    if overlap_enabled() and t % n == 0 and not isinstance(w, dict):
        out = ring_allreduce_matmul(x2, w, n, axis, resolve_chunks(n, t // n))
    else:
        out = lax.psum(_mat(x2, w), axis)
    return out.reshape(lead + out.shape[-1:])


def _tp_o_proj(a, w, tp):
    t = a.size // a.shape[-1]
    with _obs.comm_span("serve.tp_ring.o_proj",
                        nbytes=t * _mat_out_dim(w) * a.dtype.itemsize,
                        site="serve.tp_ring.o_proj"):
        return _tp_row_matmul(a, w, tp)


def _tp_down_proj(a, w, tp):
    t = a.size // a.shape[-1]
    with _obs.comm_span("serve.tp_ring.down_proj",
                        nbytes=t * _mat_out_dim(w) * a.dtype.itemsize,
                        site="serve.tp_ring.down_proj"):
        return _tp_row_matmul(a, w, tp)


def _tp_gather_logits(logits, tp):
    """All-gather vocab-sliced logits to the full vocab axis INSIDE the
    island (tiled concat in rank order — exact, no arithmetic), so the
    verify accept/commit logic computes from identical full logits on
    every rank."""
    axis, n = tp
    with _obs.comm_span("serve.tp_ring.logits",
                        nbytes=logits.size * (n - 1) * logits.dtype.itemsize,
                        site="serve.tp_ring.logits"):
        return lax.all_gather(logits, axis, axis=logits.ndim - 1,
                              tiled=True)


def llama_paged_decode_step(params, k_pool, v_pool, tables, positions,
                            ids, config: LlamaConfig, kv_scales=None,
                            tp=None):
    """One decode step over a PAGED cache: ids [B] i32, tables
    [B, max_nb] i32 block tables, positions [B] i32 = the slot each
    row's new token occupies (== its cached length; the block holding
    it must already be in the table). Per-row rope phases come from
    ``positions`` so every sequence in the batch can sit at a different
    depth — the whole point of continuous batching. Padding rows point
    their tables at null block 0 with positions 0.

    Returns (logits [B, vocab] f32, k_pool, v_pool). The pools ride
    the layer scan as carries and the Pallas kernel updates them
    in-place through input_output_aliases, so no per-layer cache copy
    exists (the conservative-aliasing trap documented in
    ops/decode_attention.py STATUS).

    With ``kv_scales=(k_scale, v_scale)`` the pools are int8: each new
    column is quantized per-kv-head OUTSIDE the kernel (the same
    kv_quant_columns bytes a prefill of the same tokens writes) and
    the fused update merges bytes + scales in place. Returns
    (logits, k_pool, v_pool, k_scale, v_scale) in that mode."""
    from ..ops.paged_attention import (_LOG2E, kv_quant_columns,
                                       paged_attend_update,
                                       paged_attend_update_quant)
    c = config
    b = ids.shape[0]
    hd = c.head_dim
    if tp is None:
        h = jnp.take(params["embed"], ids, axis=0).astype(c.dtype)  # [B, H]
    else:
        h = _tp_vocab_embed(params["embed"], ids, tp).astype(c.dtype)
    cos, sin = build_rope_cache(b, hd, base=c.rope_theta,
                                position_ids=positions[:, None])  # [B,1,·]

    def layer_step(carry, xs):
        if kv_scales is None:
            h, kp, vp = carry
        else:
            h, kp, vp, ksc, vsc = carry
        p, layer = xs
        x = fused_rms_norm(h[:, None], p["input_norm"], c.rms_norm_eps)
        if "qkv_proj" in p:
            ratio = c.num_attention_heads // c.num_key_value_heads
            nkv = _mat_out_dim(p["qkv_proj"]) // hd // (ratio + 2)
            nh = nkv * ratio
            qkv = _mat(x, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(b, 1, nh, hd)
            k = k.reshape(b, 1, nkv, hd)
            v = v.reshape(b, 1, nkv, hd)
        else:
            nh = _mat_out_dim(p["q_proj"]) // hd
            nkv = _mat_out_dim(p["k_proj"]) // hd
            q = _mat(x, p["q_proj"]).reshape(b, 1, nh, hd)
            k = _mat(x, p["k_proj"]).reshape(b, 1, nkv, hd)
            v = _mat(x, p["v_proj"]).reshape(b, 1, nkv, hd)
        kvd = nkv * hd
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        layer_i = jnp.asarray(layer, jnp.int32)
        rep = nh // nkv
        qg = q[:, 0].reshape(b, nkv, rep, hd)
        # block-diagonal q (see llama_decode_step): the paged kernel
        # reads whole [KVD, bs] slab fragments per sequence
        eye = jnp.eye(nkv, dtype=qg.dtype)
        q_bd = jnp.einsum("bgrd,ge->bgred", qg, eye).reshape(b, nh, kvd)
        qs = (q_bd.astype(jnp.float32)
              * (_LOG2E / (hd ** 0.5))).astype(q_bd.dtype)
        if kv_scales is None:
            attn_full, kp, vp = paged_attend_update(
                qs, k.reshape(b, kvd).astype(kp.dtype),
                v.reshape(b, kvd).astype(vp.dtype), kp, vp,
                tables, positions, layer_i)
        else:
            nk_q, nk_s = kv_quant_columns(k.reshape(b, kvd), nkv)
            nv_q, nv_s = kv_quant_columns(v.reshape(b, kvd), nkv)
            attn_full, kp, vp, ksc, vsc = paged_attend_update_quant(
                qs, nk_q, nv_q, nk_s, nv_s, kp, vp, ksc, vsc,
                tables, positions, layer_i)
        attn = jnp.einsum("bgred,ge->bgrd",
                          attn_full.reshape(b, nkv, rep, nkv, hd),
                          eye.astype(attn_full.dtype)).astype(c.dtype)
        ao = attn.reshape(b, nh * hd)
        attn_out = (_mat(ao, p["o_proj"]) if tp is None
                    else _tp_o_proj(ao, p["o_proj"], tp))
        h = h + attn_out
        x2 = fused_rms_norm(h[:, None], p["post_norm"], c.rms_norm_eps)[:, 0]
        gated = jax.nn.silu(_mat(x2, p["gate_proj"])) * _mat(x2, p["up_proj"])
        h = h + (_mat(gated, p["down_proj"]) if tp is None
                 else _tp_down_proj(gated, p["down_proj"], tp))
        if kv_scales is None:
            return (h, kp, vp), None
        return (h, kp, vp, ksc, vsc), None

    n_layers = k_pool.shape[0]
    xs = (params["layers"], jnp.arange(n_layers, dtype=jnp.int32))
    if kv_scales is None:
        (h, k_pool, v_pool), _ = lax.scan(
            layer_step, (h, k_pool, v_pool), xs)
        logits = llama_logits(params, h[:, None], config)[:, 0]
        return logits.astype(jnp.float32), k_pool, v_pool
    k_scale, v_scale = kv_scales
    (h, k_pool, v_pool, k_scale, v_scale), _ = lax.scan(
        layer_step, (h, k_pool, v_pool, k_scale, v_scale), xs)
    logits = llama_logits(params, h[:, None], config)[:, 0]
    return logits.astype(jnp.float32), k_pool, v_pool, k_scale, v_scale


def llama_paged_prefill_chunk(params, k_pool, v_pool, table_row, start,
                              ids, n_live, config: LlamaConfig,
                              kv_scales=None, tp=None):
    """One chunked-prefill slice for ONE sequence: ids [C] i32 padded
    to the chunk bucket, n_live (traced) real tokens, start (traced) =
    tokens already cached from earlier chunks. Scatters the chunk's KV
    into the sequence's blocks (padding tokens land in null block 0),
    attends each chunk token over cached-prefix + chunk causally via
    the gathered-context XLA path, and returns the logits of the LAST
    REAL token ([vocab] f32 — only meaningful on the final chunk) plus
    the updated pools.

    With ``kv_scales=(k_scale, v_scale)`` the pools are int8: each
    column quantizes per-kv-head via kv_quant_columns before the
    scatter (one scale per column — bytes independent of chunk
    boundaries) and the context gather dequantizes. Returns
    (logits, k_pool, v_pool, k_scale, v_scale) in that mode."""
    from ..ops.paged_attention import kv_quant_columns
    c = config
    C = ids.shape[0]
    hd = c.head_dim
    bs = k_pool.shape[-1]
    max_nb = table_row.shape[0]
    T = max_nb * bs
    if tp is None:
        h = jnp.take(params["embed"], ids, axis=0)[None].astype(c.dtype)
    else:
        h = _tp_vocab_embed(params["embed"], ids, tp)[None].astype(c.dtype)
    pidx = start + jnp.arange(C, dtype=jnp.int32)          # [C] positions
    cos, sin = build_rope_cache(C, hd, base=c.rope_theta,
                                position_ids=pidx)         # [C, hd/2]
    live = jnp.arange(C, dtype=jnp.int32) < n_live
    bid = jnp.where(live, table_row[jnp.clip(pidx // bs, 0, max_nb - 1)],
                    0).astype(jnp.int32)
    col = pidx % bs

    def layer_step(carry, xs):
        if kv_scales is None:
            h, kp, vp = carry
        else:
            h, kp, vp, ksc, vsc = carry
        p, layer = xs
        x = fused_rms_norm(h, p["input_norm"], c.rms_norm_eps)
        if "qkv_proj" in p:
            ratio = c.num_attention_heads // c.num_key_value_heads
            nkv = _mat_out_dim(p["qkv_proj"]) // hd // (ratio + 2)
            nh = nkv * ratio
            qkv = _mat(x, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(1, C, nh, hd)
            k = k.reshape(1, C, nkv, hd)
            v = v.reshape(1, C, nkv, hd)
        else:
            nh = _mat_out_dim(p["q_proj"]) // hd
            nkv = _mat_out_dim(p["k_proj"]) // hd
            q = _mat(x, p["q_proj"]).reshape(1, C, nh, hd)
            k = _mat(x, p["k_proj"]).reshape(1, C, nkv, hd)
            v = _mat(x, p["v_proj"]).reshape(1, C, nkv, hd)
        kvd = nkv * hd
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # scatter the chunk's KV columns into their blocks ([C]-indexed
        # rows over the [NP, KVD, bs] pool slab: one scatter per layer)
        if kv_scales is None:
            kp = kp.at[layer, bid, :, col].set(
                k.reshape(C, kvd).astype(kp.dtype))
            vp = vp.at[layer, bid, :, col].set(
                v.reshape(C, kvd).astype(vp.dtype))
            # gather the sequence's context (prefix + this chunk) back
            # to a contiguous slab; dead table slots read null-block
            # garbage that the causal mask kills
            kctx = jnp.transpose(kp[layer][table_row], (1, 0, 2)) \
                .reshape(kvd, T)
            vctx = jnp.transpose(vp[layer][table_row], (1, 0, 2)) \
                .reshape(kvd, T)
        else:
            nkv_ = kvd // hd
            kq, ksq = kv_quant_columns(k.reshape(C, kvd), nkv_)
            vq, vsq = kv_quant_columns(v.reshape(C, kvd), nkv_)
            kp = kp.at[layer, bid, :, col].set(kq)
            vp = vp.at[layer, bid, :, col].set(vq)
            ksc = ksc.at[layer, bid, :, col].set(ksq)
            vsc = vsc.at[layer, bid, :, col].set(vsq)
            max_nb_ = table_row.shape[0]
            bs_ = kp.shape[-1]
            kdeq = (kp[layer][table_row].astype(jnp.float32)
                    .reshape(max_nb_, nkv_, hd, bs_)
                    * ksc[layer][table_row][:, :, None, :]) \
                .reshape(max_nb_, kvd, bs_)
            vdeq = (vp[layer][table_row].astype(jnp.float32)
                    .reshape(max_nb_, nkv_, hd, bs_)
                    * vsc[layer][table_row][:, :, None, :]) \
                .reshape(max_nb_, kvd, bs_)
            kctx = jnp.transpose(kdeq, (1, 0, 2)).reshape(kvd, T) \
                .astype(c.dtype)
            vctx = jnp.transpose(vdeq, (1, 0, 2)).reshape(kvd, T) \
                .astype(c.dtype)
        rep = nh // nkv
        qg = q[0].reshape(C, nkv, rep, hd)
        kg = kctx.reshape(nkv, hd, T)
        vg = vctx.reshape(nkv, hd, T)
        s = jnp.einsum("cgrd,gdt->cgrt", qg, kg,
                       preferred_element_type=jnp.float32) / (hd ** 0.5)
        t = jnp.arange(T, dtype=jnp.int32)
        s = jnp.where((t[None, :] <= pidx[:, None])[:, None, None, :],
                      s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(vg.dtype)
        attn = jnp.einsum("cgrt,gdt->cgrd", probs, vg,
                          preferred_element_type=jnp.float32).astype(c.dtype)
        ao = attn.reshape(1, C, nh * hd)
        attn_out = (_mat(ao, p["o_proj"]) if tp is None
                    else _tp_o_proj(ao, p["o_proj"], tp))
        h = h + attn_out
        x2 = fused_rms_norm(h, p["post_norm"], c.rms_norm_eps)
        gated = jax.nn.silu(_mat(x2, p["gate_proj"])) * _mat(x2, p["up_proj"])
        h = h + (_mat(gated, p["down_proj"]) if tp is None
                 else _tp_down_proj(gated, p["down_proj"], tp))
        if kv_scales is None:
            return (h, kp, vp), None
        return (h, kp, vp, ksc, vsc), None

    n_layers = k_pool.shape[0]
    xs = (params["layers"], jnp.arange(n_layers, dtype=jnp.int32))
    if kv_scales is None:
        (h, k_pool, v_pool), _ = lax.scan(
            layer_step, (h, k_pool, v_pool), xs)
        h_last = lax.dynamic_slice_in_dim(h[0], n_live - 1, 1, 0)[None]
        logits = llama_logits(params, h_last, config)[0, 0]
        return logits.astype(jnp.float32), k_pool, v_pool
    k_scale, v_scale = kv_scales
    (h, k_pool, v_pool, k_scale, v_scale), _ = lax.scan(
        layer_step, (h, k_pool, v_pool, k_scale, v_scale), xs)
    h_last = lax.dynamic_slice_in_dim(h[0], n_live - 1, 1, 0)[None]
    logits = llama_logits(params, h_last, config)[0, 0]
    return (logits.astype(jnp.float32), k_pool, v_pool, k_scale,
            v_scale)


@functools.lru_cache(maxsize=32)
def _jitted_paged_decode(frozen):
    config = LlamaConfig(*frozen)

    def paged_decode_fn(params, kp, vp, tables, positions, ids):
        return llama_paged_decode_step(params, kp, vp, tables, positions,
                                       ids, config)
    paged_decode_fn.__name__ = "paged_decode_step"
    return jax.jit(paged_decode_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_prefill(frozen):
    config = LlamaConfig(*frozen)

    def paged_prefill_fn(params, kp, vp, table_row, start, ids, n_live):
        return llama_paged_prefill_chunk(params, kp, vp, table_row,
                                         start, ids, n_live, config)
    paged_prefill_fn.__name__ = "paged_prefill_chunk"
    return jax.jit(paged_prefill_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_decode_quant(frozen):
    config = LlamaConfig(*frozen)

    def paged_decode_quant_fn(params, kp, vp, ks, vs, tables, positions,
                              ids):
        return llama_paged_decode_step(params, kp, vp, tables, positions,
                                       ids, config, kv_scales=(ks, vs))
    paged_decode_quant_fn.__name__ = "paged_decode_step_int8"
    return jax.jit(paged_decode_quant_fn, donate_argnums=(1, 2, 3, 4))


@functools.lru_cache(maxsize=32)
def _jitted_paged_prefill_quant(frozen):
    config = LlamaConfig(*frozen)

    def paged_prefill_quant_fn(params, kp, vp, ks, vs, table_row, start,
                               ids, n_live):
        return llama_paged_prefill_chunk(params, kp, vp, table_row,
                                         start, ids, n_live, config,
                                         kv_scales=(ks, vs))
    paged_prefill_quant_fn.__name__ = "paged_prefill_chunk_int8"
    return jax.jit(paged_prefill_quant_fn, donate_argnums=(1, 2, 3, 4))


# KV/scale pools [L, NP, NKV*HD|NKV, bs] shard their kv-head-major axis
# 2 across 'mp' — each rank runs the unchanged paged kernels (and their
# shape-priced _fit_* fitters) on its head shard with the SAME
# rank-replicated block tables, so BlockPool / PrefixCache / the commit
# schedule stay host-side and rank-agnostic.
_TP_POOL_SPEC = P(None, None, "mp", None)


def _tp_specs(config: LlamaConfig, mesh: Mesh):
    """(param pspec tree, ``tp`` tuple) for a serving island: weights
    sliced per param_pspecs over 'mp' alone (no fsdp inside the serving
    mesh). The trees only match PLAIN param arrays — the engine rejects
    fused/int8 weight dicts under TP at init."""
    n = int(mesh.shape["mp"])
    return param_pspecs(config, ParallelConfig(mp=n)), ("mp", n)


@functools.lru_cache(maxsize=32)
def _jitted_paged_decode_tp(frozen, mesh):
    """mp-sharded twin of _jitted_paged_decode: one fully-manual
    shard_map island per decode step (the paged Pallas kernels cannot be
    auto-partitioned under GSPMD). Logits leave vocab-sharded
    P(None, 'mp') — the engine's host argmax reads the exact concat."""
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, tables, positions, ids):
        return llama_paged_decode_step(params, kp, vp, tables, positions,
                                       ids, config, tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, rep, rep, rep),
        out_specs=(P(None, "mp"), _TP_POOL_SPEC, _TP_POOL_SPEC),
        check_vma=False)

    def paged_decode_tp_fn(params, kp, vp, tables, positions, ids):
        return body(params, kp, vp, tables, positions, ids)
    paged_decode_tp_fn.__name__ = "paged_decode_step_tp"
    return jax.jit(paged_decode_tp_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_decode_quant_tp(frozen, mesh):
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, ks, vs, tables, positions, ids):
        return llama_paged_decode_step(params, kp, vp, tables, positions,
                                       ids, config, kv_scales=(ks, vs),
                                       tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, _TP_POOL_SPEC,
                  _TP_POOL_SPEC, rep, rep, rep),
        out_specs=(P(None, "mp"), _TP_POOL_SPEC, _TP_POOL_SPEC,
                   _TP_POOL_SPEC, _TP_POOL_SPEC),
        check_vma=False)

    def paged_decode_quant_tp_fn(params, kp, vp, ks, vs, tables,
                                 positions, ids):
        return body(params, kp, vp, ks, vs, tables, positions, ids)
    paged_decode_quant_tp_fn.__name__ = "paged_decode_step_int8_tp"
    return jax.jit(paged_decode_quant_tp_fn, donate_argnums=(1, 2, 3, 4))


@functools.lru_cache(maxsize=32)
def _jitted_paged_prefill_tp(frozen, mesh):
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, table_row, start, ids, n_live):
        return llama_paged_prefill_chunk(params, kp, vp, table_row,
                                         start, ids, n_live, config,
                                         tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, rep, rep, rep,
                  rep),
        out_specs=(P("mp"), _TP_POOL_SPEC, _TP_POOL_SPEC),
        check_vma=False)

    def paged_prefill_tp_fn(params, kp, vp, table_row, start, ids,
                            n_live):
        return body(params, kp, vp, table_row, start, ids, n_live)
    paged_prefill_tp_fn.__name__ = "paged_prefill_chunk_tp"
    return jax.jit(paged_prefill_tp_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_prefill_quant_tp(frozen, mesh):
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, ks, vs, table_row, start, ids, n_live):
        return llama_paged_prefill_chunk(params, kp, vp, table_row,
                                         start, ids, n_live, config,
                                         kv_scales=(ks, vs), tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, _TP_POOL_SPEC,
                  _TP_POOL_SPEC, rep, rep, rep, rep),
        out_specs=(P("mp"), _TP_POOL_SPEC, _TP_POOL_SPEC, _TP_POOL_SPEC,
                   _TP_POOL_SPEC),
        check_vma=False)

    def paged_prefill_quant_tp_fn(params, kp, vp, ks, vs, table_row,
                                  start, ids, n_live):
        return body(params, kp, vp, ks, vs, table_row, start, ids,
                    n_live)
    paged_prefill_quant_tp_fn.__name__ = "paged_prefill_chunk_int8_tp"
    return jax.jit(paged_prefill_quant_tp_fn, donate_argnums=(1, 2, 3, 4))


# ---------------------------------------------------------------------------
# speculative decoding (PR 18): draft model + batched paged verification
# ---------------------------------------------------------------------------

def make_draft_model(params, config: LlamaConfig, num_layers: int = 1):
    """Default draft model for speculative decoding: the base model's
    FIRST ``num_layers`` decoder layers, sharing the embedding, final
    norm and lm head by reference (no copy — the stacked-leaf layout
    makes the truncation a view-style slice per leaf).

    A truncated self-draft needs no extra training to correlate with
    the base argmax, and the PARITY contract makes its quality a pure
    latency knob: verification re-derives every emitted token from the
    base model, so ANY draft — this one, separately trained weights, or
    garbage — yields bit-identical streams. Returns (draft_params,
    draft_config)."""
    dl = max(1, min(int(num_layers), config.num_hidden_layers))
    dcfg = dataclasses.replace(config, num_hidden_layers=dl)
    dparams = {
        "embed": params["embed"],
        "layers": {k: v[:dl] for k, v in params["layers"].items()},
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        dparams["lm_head"] = params["lm_head"]
    return dparams, dcfg


def llama_paged_verify_step(params, k_pool, v_pool, tables, qstart,
                            t_live, fed, config: LlamaConfig,
                            kv_scales=None, tp=None):
    """Score T fed tokens per sequence in ONE base-model pass over a
    paged cache, greedily accept/reject, and commit only accepted KV.

    fed [B, T] i32 — fed[:, 0] is each row's last emitted token (its KV
    is NOT yet cached), fed[:, 1:] the draft's proposals; qstart [B]
    i32 cached token counts (fed[:, j] sits at position qstart + j);
    t_live [B] i32 live fed counts (1 = plain decode through this
    path, 0 = padding row: tables at null block 0, qstart 0).

    Attention splits into the cached prefix — the multi-token paged
    kernel returns online-softmax partials — and the tiny [T, T] causal
    fed block computed here in XLA, merged exactly
    (ops/paged_attention.merge_verify_partials). The greedy accept rule
    takes the longest prefix where the base argmax equals the draft
    proposal, then the base's correction token: out[:, j] is the base's
    next-token argmax after position qstart + j, and
    commit_len = accepted proposals + 1 counts the fed tokens whose KV
    is committed (the correction token's KV is NOT cached — it is the
    next iteration's fed[:, 0], exactly like sequential decode).

    Returns (out [B, T] i32, commit_len [B] i32, fin_ok [B] bool,
    k_pool, v_pool) — the emitted tokens for row b are
    out[b, :commit_len[b]]; fin_ok flags rows whose logits were all
    finite (the engine's poison screen — it never sees logits). With
    ``kv_scales=(k_scale, v_scale)`` the pools are int8: fed columns
    quantize OUTSIDE the kernels via kv_quant_columns and the fed-block
    attention reads the DEQUANTIZED values, so both the committed bytes
    and the numerics each token sees match sequential int8 decode.
    Returns (out, commit_len, fin_ok, k_pool, v_pool, k_scale,
    v_scale) in that mode."""
    from ..ops.paged_attention import (_LOG2E, kv_quant_columns,
                                       merge_verify_partials,
                                       paged_attention_verify,
                                       paged_attention_verify_quant,
                                       paged_verify_commit,
                                       paged_verify_commit_quant)
    c = config
    B, T = fed.shape
    hd = c.head_dim
    if tp is None:
        h = jnp.take(params["embed"], fed, axis=0).astype(c.dtype)  # [B,T,H]
    else:
        h = _tp_vocab_embed(params["embed"], fed, tp).astype(c.dtype)
    pos2d = qstart[:, None] + jnp.arange(T, dtype=jnp.int32)    # [B,T]
    cos, sin = build_rope_cache(T, hd, base=c.rope_theta,
                                position_ids=pos2d)             # [B,T,hd/2]
    # dead-row guard: a padding row's kernel outputs are unwritten, so
    # zero its cached-side partials (anchor -1e30 rescales to exactly 0)
    live3 = (qstart > 0)[:, None, None]

    def layer_step(carry, xs):
        # pools are closure-captured read-only here (the commit below is
        # the single writer), so the carry holds just the hidden state
        h, = carry
        p, layer = xs
        x = fused_rms_norm(h, p["input_norm"], c.rms_norm_eps)
        if "qkv_proj" in p:
            ratio = c.num_attention_heads // c.num_key_value_heads
            nkv = _mat_out_dim(p["qkv_proj"]) // hd // (ratio + 2)
            nh = nkv * ratio
            qkv = _mat(x, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(B, T, nh, hd)
            k = k.reshape(B, T, nkv, hd)
            v = v.reshape(B, T, nkv, hd)
        else:
            nh = _mat_out_dim(p["q_proj"]) // hd
            nkv = _mat_out_dim(p["k_proj"]) // hd
            q = _mat(x, p["q_proj"]).reshape(B, T, nh, hd)
            k = _mat(x, p["k_proj"]).reshape(B, T, nkv, hd)
            v = _mat(x, p["v_proj"]).reshape(B, T, nkv, hd)
        kvd = nkv * hd
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        layer_i = jnp.asarray(layer, jnp.int32)
        rep = nh // nkv
        # t-major block-diagonal rows: row t*NH + i is fed token t's
        # head-i query against whole [KVD, bs] slab fragments
        qg = q.reshape(B, T, nkv, rep, hd)
        eye = jnp.eye(nkv, dtype=qg.dtype)
        q_bd = jnp.einsum("btgrd,ge->btgred", qg, eye) \
            .reshape(B, T * nh, kvd)
        qs = (q_bd.astype(jnp.float32)
              * (_LOG2E / (hd ** 0.5))).astype(q_bd.dtype)
        if kv_scales is None:
            acc_c, m_c, l_c = paged_attention_verify(
                qs, k_pool, v_pool, tables, qstart, layer_i)
            # fed columns AS STORED (pool dtype round-trip): the exact
            # values sequential decode would read back from the cache
            k_st = k.reshape(B, T, kvd).astype(k_pool.dtype)
            v_st = v.reshape(B, T, kvd).astype(v_pool.dtype)
            kf = k_st.astype(jnp.float32)
            vf = v_st.astype(jnp.float32)
            ys = (k_st, v_st)
        else:
            ksc, vsc = kv_scales
            kq, ksq = kv_quant_columns(k.reshape(B * T, kvd), nkv)
            vq, vsq = kv_quant_columns(v.reshape(B * T, kvd), nkv)
            kq = kq.reshape(B, T, kvd)
            vq = vq.reshape(B, T, kvd)
            ksq = ksq.reshape(B, T, nkv)
            vsq = vsq.reshape(B, T, nkv)
            acc_c, m_c, l_c = paged_attention_verify_quant(
                qs, k_pool, v_pool, ksc, vsc, tables, qstart, layer_i)
            kf = (kq.astype(jnp.float32).reshape(B, T, nkv, hd)
                  * ksq[..., None]).reshape(B, T, kvd)
            vf = (vq.astype(jnp.float32).reshape(B, T, nkv, hd)
                  * vsq[..., None]).reshape(B, T, kvd)
            ys = (kq, vq, ksq, vsq)
        # fed-token causal attention in XLA: block-diagonal q rows make
        # the GQA head selection automatic in the [KVD] dot
        s_f = jnp.einsum("brk,buk->bru", qs.astype(jnp.float32), kf)
        t_row = jnp.arange(T * nh, dtype=jnp.int32) // nh      # [R]
        causal = (jnp.arange(T, dtype=jnp.int32)[None, :]
                  <= t_row[:, None])                           # [R,T]
        s_f = jnp.where(causal[None], s_f, jnp.float32(-1e30))
        m_f = s_f.max(axis=-1, keepdims=True)
        p_f = jnp.exp2(s_f - m_f)
        l_f = p_f.sum(axis=-1, keepdims=True)
        acc_f = jnp.einsum("bru,buk->brk", p_f, vf)
        attn_rows = merge_verify_partials(
            jnp.where(live3, acc_c, 0.0),
            jnp.where(live3, m_c[:, :, :1], jnp.float32(-1e30)),
            jnp.where(live3, l_c[:, :, :1], 0.0),
            acc_f, m_f, l_f)                                   # [B,R,KVD]
        attn = jnp.einsum("btgred,ge->btgrd",
                          attn_rows.reshape(B, T, nkv, rep, nkv, hd),
                          eye.astype(attn_rows.dtype)).astype(c.dtype)
        ao = attn.reshape(B, T, nh * hd)
        attn_out = (_mat(ao, p["o_proj"]) if tp is None
                    else _tp_o_proj(ao, p["o_proj"], tp))
        h = h + attn_out
        x2 = fused_rms_norm(h, p["post_norm"], c.rms_norm_eps)
        gated = jax.nn.silu(_mat(x2, p["gate_proj"])) * _mat(x2, p["up_proj"])
        h = h + (_mat(gated, p["down_proj"]) if tp is None
                 else _tp_down_proj(gated, p["down_proj"], tp))
        return (h,), ys

    n_layers = k_pool.shape[0]
    xs = (params["layers"], jnp.arange(n_layers, dtype=jnp.int32))
    (h,), cols = lax.scan(layer_step, (h,), xs)
    logits = llama_logits(params, h, config).astype(jnp.float32)
    if tp is not None:
        # full-vocab logits on every rank (exact concat) so the argmax /
        # accept / commit_len below — and hence the commit kernel each
        # rank drives on its pool shard — are rank-identical
        logits = _tp_gather_logits(logits, tp)
    # per-row finite screen: the engine sees tokens, not logits, so the
    # poison/quarantine contract needs the flag computed here
    fin_ok = jnp.isfinite(logits).all(axis=(1, 2))             # [B]
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)        # [B,T]
    # longest prefix where base argmax == draft proposal (both within
    # the live window), then the base's correction token
    if T > 1:
        match = ((out[:, :-1] == fed[:, 1:])
                 & (jnp.arange(1, T, dtype=jnp.int32)[None, :]
                    < t_live[:, None]))
        accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                           axis=1)
    else:
        accepted = jnp.zeros((B,), jnp.int32)
    commit_len = jnp.where(t_live > 0, accepted + 1, 0).astype(jnp.int32)
    if kv_scales is None:
        k_cols, v_cols = cols
        kp, vp = paged_verify_commit(k_cols, v_cols, k_pool, v_pool,
                                     tables, qstart, commit_len)
        return out, commit_len, fin_ok, kp, vp
    kq_cols, vq_cols, ks_cols, vs_cols = cols
    k_scale, v_scale = kv_scales
    kp, vp, ks, vs = paged_verify_commit_quant(
        kq_cols, vq_cols, ks_cols, vs_cols, k_pool, v_pool,
        k_scale, v_scale, tables, qstart, commit_len)
    return out, commit_len, fin_ok, kp, vp, ks, vs


@functools.lru_cache(maxsize=32)
def _jitted_paged_verify(frozen):
    config = LlamaConfig(*frozen)

    def paged_verify_fn(params, kp, vp, tables, qstart, t_live, fed):
        return llama_paged_verify_step(params, kp, vp, tables, qstart,
                                       t_live, fed, config)
    paged_verify_fn.__name__ = "paged_verify_step"
    return jax.jit(paged_verify_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_verify_quant(frozen):
    config = LlamaConfig(*frozen)

    def paged_verify_quant_fn(params, kp, vp, ks, vs, tables, qstart,
                              t_live, fed):
        return llama_paged_verify_step(params, kp, vp, tables, qstart,
                                       t_live, fed, config,
                                       kv_scales=(ks, vs))
    paged_verify_quant_fn.__name__ = "paged_verify_step_int8"
    return jax.jit(paged_verify_quant_fn, donate_argnums=(1, 2, 3, 4))


@functools.lru_cache(maxsize=32)
def _jitted_paged_verify_tp(frozen, mesh):
    """mp-sharded verify: logits all-gather in-island (exact vocab
    concat) so out/commit_len/fin_ok are computed rank-identically and
    each rank drives the commit kernel on its pool shard with the same
    schedule — they leave the island replicated."""
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, tables, qstart, t_live, fed):
        return llama_paged_verify_step(params, kp, vp, tables, qstart,
                                       t_live, fed, config, tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, rep, rep, rep,
                  rep),
        out_specs=(rep, rep, rep, _TP_POOL_SPEC, _TP_POOL_SPEC),
        check_vma=False)

    def paged_verify_tp_fn(params, kp, vp, tables, qstart, t_live, fed):
        return body(params, kp, vp, tables, qstart, t_live, fed)
    paged_verify_tp_fn.__name__ = "paged_verify_step_tp"
    return jax.jit(paged_verify_tp_fn, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=32)
def _jitted_paged_verify_quant_tp(frozen, mesh):
    config = LlamaConfig(*frozen)
    pspecs, tp = _tp_specs(config, mesh)
    rep = P()

    def step(params, kp, vp, ks, vs, tables, qstart, t_live, fed):
        return llama_paged_verify_step(params, kp, vp, tables, qstart,
                                       t_live, fed, config,
                                       kv_scales=(ks, vs), tp=tp)

    body = shard_map(
        step, mesh=mesh,
        in_specs=(pspecs, _TP_POOL_SPEC, _TP_POOL_SPEC, _TP_POOL_SPEC,
                  _TP_POOL_SPEC, rep, rep, rep, rep),
        out_specs=(rep, rep, rep, _TP_POOL_SPEC, _TP_POOL_SPEC,
                   _TP_POOL_SPEC, _TP_POOL_SPEC),
        check_vma=False)

    def paged_verify_quant_tp_fn(params, kp, vp, ks, vs, tables, qstart,
                                 t_live, fed):
        return body(params, kp, vp, ks, vs, tables, qstart, t_live, fed)
    paged_verify_quant_tp_fn.__name__ = "paged_verify_step_int8_tp"
    return jax.jit(paged_verify_quant_tp_fn, donate_argnums=(1, 2, 3, 4))


def generate_scan(params, cache, first_token, num_tokens,
                  config: LlamaConfig):
    """Generate ``num_tokens`` greedily INSIDE one jit: lax.scan over decode
    steps, so a whole generation is a single device dispatch (the per-token
    host round-trip through the remote-TPU tunnel costs ~5 ms each).

    first_token: [B, 1] int32 (normally argmax of the prefill logits).
    Returns (tokens [B, num_tokens], cache).
    """
    params = _decode_weights(params, config)

    def step(carry, _):
        cache, tok = carry
        logits, cache = llama_decode_step(params, cache, tok, config)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        return (cache, nxt), nxt[:, 0]

    (cache, _), toks = lax.scan(step, (cache, first_token),
                                None, length=num_tokens)
    return toks.T, cache


def sample_logits(logits, key, temperature=1.0, top_k=0, top_p=1.0):
    """One sampling step on [B, vocab] fp32 logits (ref: the reference's
    sampling decode — paddle top_k/top_p generation). top_k=0 disables the
    k cut; top_p=1.0 disables the nucleus cut; both compose (k first, then
    p over the surviving mass, reference order). Runs INSIDE jit (all
    branches static)."""
    z = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k < z.shape[-1]:
        kth = jnp.sort(z, axis=-1)[:, -top_k][:, None]
        z = jnp.where(z < kth, -jnp.inf, z)
    # nucleus cut, traced-top_p-safe: keep the smallest prefix with mass
    # >= top_p (the token crossing the threshold stays — reference
    # semantics); top_p >= 1.0 keeps everything (cut lands on -inf tail)
    sorted_z = jnp.sort(z, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_z, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cut = jnp.sum(cum < top_p, axis=-1, keepdims=True)
    cut = jnp.minimum(cut, z.shape[-1] - 1)
    thresh = jnp.take_along_axis(sorted_z, cut, axis=-1)
    z = jnp.where(z < thresh, -jnp.inf, z)
    return jax.random.categorical(key, z, axis=-1).astype(jnp.int32)


def sample_scan(params, cache, first_logits, num_tokens, config, key,
                temperature=1.0, top_k=0, top_p=1.0):
    """Sampling counterpart of generate_scan: the whole continuation is one
    device dispatch; the PRNG key splits per step inside the scan."""
    params = _decode_weights(params, config)

    def step(carry, _):
        cache, tok, key = carry
        key, sub = jax.random.split(key)
        logits, cache = llama_decode_step(params, cache, tok, config)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)[:, None]
        return (cache, nxt, key), nxt[:, 0]

    key, sub = jax.random.split(key)
    first = sample_logits(first_logits, sub, temperature, top_k,
                          top_p)[:, None]
    (cache, _, _), toks = lax.scan(step, (cache, first, key),
                                   None, length=num_tokens - 1)
    return jnp.concatenate([first, toks.T], axis=1), cache


def sample_generate(params, prompt_ids, config: LlamaConfig, max_new_tokens,
                    temperature=1.0, top_k=0, top_p=1.0, seed=0,
                    max_len=None):
    """Sampling generation with the same one-dispatch structure as
    greedy_generate (prefill fills the cache, the continuation is a single
    compiled scan). Deterministic for a fixed seed."""
    bucket = generate_scan_bucket(max_new_tokens + 1)  # all sampled steps
    prompt, logits, cache, frozen = _prefill_for_generate(
        params, prompt_ids, config, max_new_tokens, max_len,
        bucket, "sample_generate")
    if logits is None:
        return np.zeros((prompt.shape[0], 0), np.int32)
    key = jax.random.PRNGKey(seed)
    # temperature/top_p ride as TRACED scalars (shape-neutral): varying
    # them per request reuses one compiled scan; only top_k is static
    # (it sizes the sort cut)
    toks, _ = _jitted_sample(frozen, bucket, int(top_k))(
        params, cache, logits, key, jnp.float32(temperature),
        jnp.float32(top_p))
    return np.asarray(toks)[:, :max_new_tokens]


@functools.lru_cache(maxsize=32)
def _jitted_sample(frozen, num_tokens, top_k):
    config = LlamaConfig(*frozen)

    def sample_scan_fn(params, cache, first_logits, key, temperature, top_p):
        return sample_scan(params, cache, first_logits, num_tokens, config,
                           key, temperature, top_k, top_p)
    sample_scan_fn.__name__ = "sample_scan"
    return jax.jit(sample_scan_fn, donate_argnums=(1,))


def _prefill_for_generate(params, prompt_ids, config, max_new_tokens,
                          max_len, extra_len, caller):
    """Shared generation preamble: validation, cache sizing, prefill.
    Returns (prompt, logits, cache, frozen) or a [B, 0] early result."""
    prompt = np.asarray(prompt_ids)
    b, plen = prompt.shape
    if plen == 0:
        raise ValueError(f"{caller}: prompt must be non-empty")
    if max_new_tokens <= 0:
        return prompt, None, None, None
    max_len = max_len or (plen + max_new_tokens)
    if max_len < plen + max_new_tokens:
        raise ValueError(
            f"{caller}: max_len={max_len} < prompt {plen} + "
            f"max_new_tokens {max_new_tokens}; the cache would overflow")
    frozen = _freeze_config(config)
    # 128-ALIGNED cache extents: the fused Pallas attend+update decode
    # kernel (ops/decode_attention.py) needs them, and its pos-clamped
    # DMA never reads the padding. (The XLA einsum FALLBACK prefers
    # ragged extents — aligned ones re-introduce a V-slice relayout
    # copy, 1.90 vs 2.52 ms/step at hd64 b8 — but the fallback only
    # runs when a caller forces a non-128-multiple max_len. PARITY.md
    # r5 decode notes have the full story.)
    cache_len = -(-max(max_len, plen + extra_len) // 128) * 128
    cache = init_kv_cache(config, b, cache_len)
    logits, cache = _jitted_prefill(frozen)(params, cache,
                                            jnp.asarray(prompt))
    return prompt, logits, cache, frozen


def greedy_generate(params, prompt_ids, config: LlamaConfig, max_new_tokens,
                    max_len=None):
    """Greedy decoding: one batched prefill pass fills the KV cache (one
    compile per distinct prompt length), then the whole continuation runs as
    a single compiled lax.scan dispatch (generate_scan). num_tokens is
    bucketed to powers of two so sweeping max_new_tokens doesn't recompile
    per value; both jitted wrappers donate the cache for in-place k/v."""
    n_cont = max_new_tokens - 1
    bucket = generate_scan_bucket(max_new_tokens)
    prompt, logits, cache, frozen = _prefill_for_generate(
        params, prompt_ids, config, max_new_tokens, max_len,
        bucket, "greedy_generate")
    if logits is None:
        return np.zeros((prompt.shape[0], 0), np.int32)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    if max_new_tokens == 1:
        return np.asarray(first)
    toks, cache = _jitted_generate(frozen, bucket)(params, cache, first)
    return np.concatenate([np.asarray(first), np.asarray(toks)[:, :n_cont]],
                          axis=1)


def generate_scan_bucket(max_new_tokens: int) -> int:
    """Number of decode-scan steps greedy_generate compiles for: the
    continuation length (max_new_tokens - 1, the first token comes from
    prefill) rounded UP to a power of two, so nearby values share one
    executable; extra steps run past the last wanted token (sequential
    scan) and the output is sliced. Benchmarks divide the scan's device
    time by this."""
    n_cont = max_new_tokens - 1
    return 1 << (n_cont - 1).bit_length() if n_cont > 0 else 0


def _freeze_config(config):
    return dataclasses.astuple(config)


@functools.lru_cache(maxsize=32)
def _jitted_prefill(frozen):
    config = LlamaConfig(*frozen)

    # a NAMED wrapper (not functools.partial, which loses __name__): the
    # profiler device span must read jit_llama_prefill / jit_generate_scan
    # so benchmarks can time the phases separately (bench.run_decode)
    def llama_prefill_fn(params, cache, ids):
        return llama_prefill(params, cache, ids, config=config)
    llama_prefill_fn.__name__ = "llama_prefill"
    return jax.jit(llama_prefill_fn, donate_argnums=(1,))


@functools.lru_cache(maxsize=32)
def _jitted_generate(frozen, num_tokens):
    config = LlamaConfig(*frozen)

    def generate_scan_fn(params, cache, first):
        return generate_scan(params, cache, first, num_tokens, config)
    generate_scan_fn.__name__ = "generate_scan"
    return jax.jit(generate_scan_fn, donate_argnums=(1,))


# ---------------------------------------------------------------------------
# compiled SPMD train step
# ---------------------------------------------------------------------------

def make_mesh(parallel: ParallelConfig, devices=None) -> Mesh:
    from ..distributed.fleet.topology import _pick_devices
    n = parallel.total
    devs = list(devices) if devices is not None else _pick_devices(n)
    arr = np.array(devs[:n]).reshape(parallel.dp, parallel.pp,
                                     parallel.sharding, parallel.sep,
                                     parallel.mp)
    return Mesh(arr, axis_names=("dp", "pp", "sharding", "sep", "mp"))


def _adamw_init(params, multi_precision=True):
    """multi_precision=True (reference default) keeps f32 moments for
    every param; False stores moments in each param's own dtype, halving
    optimizer HBM streaming on bf16 stacks. The update always COMPUTES
    in f32 (see _adamw_update) — only the stored state narrows."""
    def mdtype(p):
        return jnp.float32 if multi_precision else p.dtype
    return {
        "m": jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, mdtype(p)), params),
        "v": jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, mdtype(p)), params),
        "t": jnp.zeros((), jnp.float32),
    }


def _adamw_update(params, grads, state, lr, b1=0.9, b2=0.95, eps=1e-8, wd=0.1,
                  masks=None):
    """``masks`` (optional) is a pytree shaped like ``params`` whose leaves
    are either None (update normally) or a bool array broadcastable over
    the leaf's LEADING dims — False rows freeze: param AND moments pass
    through bitwise-unchanged (select, not a zero-grad update, so frozen
    moments do not decay and no moment read-modify-write bandwidth is
    spent on them under XLA's fusion). PR 10 uses this with the
    per-expert ``moe_expert_rows`` stats so only experts that actually
    routed tokens this step stream their f32 AdamW moments; touched rows
    are bitwise-identical to the unmasked update. The shared step count
    ``t`` (and thus the bias-correction powers) still advances globally —
    the standard lazy/sparse-Adam semantics."""
    t = state["t"] + 1

    def upd(p, g, m, v, mask):
        g32 = g.astype(jnp.float32)
        # compute in f32; store back in the state's dtype (f32 under
        # multi_precision — a no-op cast, bit-identical to the old path)
        m_new = b1 * m.astype(jnp.float32) + (1 - b1) * g32
        v_new = b2 * v.astype(jnp.float32) + (1 - b2) * g32 * g32
        m_hat = m_new / (1 - b1 ** t)
        v_hat = v_new / (1 - b2 ** t)
        p32 = p.astype(jnp.float32)
        p_new = p32 - lr * (m_hat / (jnp.sqrt(v_hat) + eps) + wd * p32)
        if mask is not None:
            keep = mask.reshape(mask.shape + (1,) * (p.ndim - mask.ndim))
            # select (not multiply): frozen rows must be BITWISE the old
            # values (f32<->storage round-trips are exact)
            p_new = jnp.where(keep, p_new, p32)
            m_new = jnp.where(keep, m_new, m.astype(jnp.float32))
            v_new = jnp.where(keep, v_new, v.astype(jnp.float32))
        return p_new.astype(p.dtype), m_new.astype(m.dtype), \
            v_new.astype(v.dtype)

    flat_p, tree = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_leaves(grads)
    flat_m = jax.tree_util.tree_leaves(state["m"])
    flat_v = jax.tree_util.tree_leaves(state["v"])
    if masks is None:
        flat_k = [None] * len(flat_p)
    else:
        flat_k = jax.tree_util.tree_flatten(
            masks, is_leaf=lambda x: x is None)[0]
    out = [upd(p, g, m, v, kp) for p, g, m, v, kp
           in zip(flat_p, flat_g, flat_m, flat_v, flat_k)]
    new_p = jax.tree_util.tree_unflatten(tree, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(tree, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(tree, [o[2] for o in out])
    return new_p, {"m": new_m, "v": new_v, "t": t}


def build_train_step(config: LlamaConfig, parallel: ParallelConfig,
                     mesh: Optional[Mesh] = None, lr: float = 3e-4,
                     seed: int = 0):
    """Returns (step_fn, params, opt_state). step_fn(params, opt, ids, labels)
    -> (params, opt, loss), jit-compiled over the mesh with full dp/mp/
    sharding/sep/pp shardings. ids/labels: [B, S] int32 host arrays.
    """
    if mesh is None and parallel.total > 1:
        mesh = make_mesh(parallel)
    use_flash = parallel.use_flash
    if use_flash is None:
        from ..ops._common import interpret_mode
        use_flash = not interpret_mode()

    if parallel.sep > 1:
        # validate the strategy (env or config field) BEFORE any tracing so
        # a typo'd PADDLE_TPU_SEP_STRATEGY fails with the variable named,
        # not deep inside the shard_map island
        from ..parallel.ulysses_attention import resolve_sep_strategy
        if (resolve_sep_strategy(parallel.sep_strategy) == "ulysses"
                and config.num_attention_heads % parallel.sep):
            raise ValueError(
                f"ulysses sep strategy needs num_heads % sep == 0 for the "
                f"all-to-all head split; got num_heads="
                f"{config.num_attention_heads}, sep={parallel.sep}. Pick a "
                f"sep degree dividing the head count or select the ring "
                f"strategy (sep_strategy='ring' / PADDLE_TPU_SEP_STRATEGY="
                f"ring).")

    params = init_llama_params(config, seed)
    pspecs = param_pspecs(config, parallel)

    if parallel.pp > 1:
        return _build_pp_train_step(config, parallel, mesh, params, pspecs,
                                    lr, use_flash)

    opt_specs = opt_state_pspecs(config, parallel, pspecs)
    if mesh is not None:
        params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, pspecs, is_leaf=lambda x: not isinstance(x, dict))
    opt_state = _adamw_init(params)
    if mesh is not None:
        opt_state["m"] = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            opt_state["m"], opt_specs, is_leaf=lambda x: not isinstance(x, dict))
        opt_state["v"] = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            opt_state["v"], opt_specs, is_leaf=lambda x: not isinstance(x, dict))

    needs_shard_map = parallel.sep > 1

    def loss_fn(p, ids, labels):
        if needs_shard_map:
            from .._compat import shard_map
            # FULLY manual island: 'sep' (ring attention does explicit
            # ppermute) and the batch axes carry real sharding; a dp-
            # sharded batch entering a manual region on an AUTO axis
            # CHECK-fails XLA's SPMD group expansion (spmd_partitioner_
            # util.cc:495, seen at the dp2·sep2·mp2 factoring), and any
            # leftover auto axis turns lax.axis_index into a PartitionId
            # instruction the SPMD partitioner rejects as UNIMPLEMENTED.
            # mp-sharded params enter on P() specs, i.e. gathered at the
            # boundary and computed replicated across mp inside — the
            # sep>1 factorings trade TP inside this island for a working
            # partition (the pp path keeps explicit TP via tp_axis).
            batch_axes = _act_spec(parallel)[0]
            if isinstance(batch_axes, str):  # P collapses 1-tuples
                batch_axes = (batch_axes,)
            manual = frozenset(mesh.axis_names)
            sep_only = jax.tree_util.tree_map(
                lambda _: P(), pspecs, is_leaf=lambda x: isinstance(x, P))
            smap = shard_map(
                functools.partial(llama_loss, config=config, parallel=parallel,
                                  mesh=None, use_flash=use_flash,
                                  in_shard_map=True,
                                  loss_psum_axes=("sep",) + tuple(batch_axes)),
                mesh=mesh,
                in_specs=(sep_only, P(batch_axes, "sep"),
                          P(batch_axes, "sep")),
                out_specs=P(),
                axis_names=manual,
                check_vma=False)
            with _obs.comm_span("llama.sep_island",
                                nbytes=ids.size * ids.dtype.itemsize,
                                site="llama.sep_island"):
                return smap(p, ids, labels)
        return llama_loss(p, ids, labels, config, parallel, mesh,
                          use_flash=use_flash)

    def step(p, opt, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
        if mesh is not None:
            # pin grads to the PARAM specs: the backward layer-scan
            # otherwise accumulates stacked-layer grads in whatever
            # sharding propagation picked (an L-dim split, observed as
            # "[SPMD] Involuntary full rematerialization ... %fake_
            # parameter f32[L,H,H]" in the r4 dryrun) and pays a
            # replicate-and-reslice at the optimizer boundary; the
            # constraint propagates into the while-loop state so the
            # accumulator is laid out like the update wants it
            grads = jax.tree_util.tree_map(
                lambda g, s: lax.with_sharding_constraint(
                    g, NamedSharding(mesh, s)),
                grads, pspecs, is_leaf=lambda x: not isinstance(x, dict))
        new_p, new_opt = _adamw_update(p, grads, opt, lr)
        return new_p, new_opt, loss

    batch_sharding = (NamedSharding(mesh, P(_act_spec(parallel)[0], None))
                      if mesh is not None else None)
    jit_step = jax.jit(step, donate_argnums=(0, 1))

    def step_fn(p, opt, ids, labels):
        ids = jnp.asarray(ids, jnp.int32)
        labels = jnp.asarray(labels, jnp.int32)
        if batch_sharding is not None:
            ids = jax.device_put(ids, batch_sharding)
            labels = jax.device_put(labels, batch_sharding)
        return jit_step(p, opt, ids, labels)

    return step_fn, params, opt_state


def _build_pp_train_step(config, parallel, mesh, params, pspecs, lr, use_flash):
    """Pipeline path: stage-stacked params sharded over 'pp', collective
    schedule via shard_map + ppermute (parallel/pipeline.py design), every
    mesh axis manual inside the island (batch axes handled by explicit loss
    psums — see manual_axes below)."""
    from .._compat import shard_map
    c = config
    S = parallel.pp
    L = c.num_hidden_layers
    assert L % S == 0, (L, S)
    per = L // S
    M = max(parallel.microbatches, S)

    # reshape stacked layers [L, ...] -> [S, per, ...] and shard axis0 on 'pp'
    def restage(a):
        return a.reshape((S, per) + a.shape[1:])

    params = dict(params)
    params["layers"] = jax.tree_util.tree_map(restage, params["layers"])
    layer_specs = jax.tree_util.tree_map(
        lambda s: P(*(("pp",) + tuple(s))), pspecs["layers"],
        is_leaf=lambda x: isinstance(x, P))
    pspecs = dict(pspecs)
    pspecs["layers"] = layer_specs

    if mesh is not None:
        params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, pspecs, is_leaf=lambda x: not isinstance(x, dict))
    opt_state = _adamw_init(params)
    if mesh is not None:
        for key in ("m", "v"):
            opt_state[key] = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
                opt_state[key], pspecs, is_leaf=lambda x: not isinstance(x, dict))

    act = _act_spec(parallel)
    batch_axes = act[0]
    if isinstance(batch_axes, str):  # P collapses 1-tuples
        batch_axes = (batch_axes,)
    tp_axis = "mp" if parallel.mp > 1 else None
    sep_on = parallel.sep > 1
    loss_psum_axes = (("sep",) if sep_on else ()) + tuple(batch_axes)

    def stage_fn(stage_params, h, cos, sin):
        body = functools.partial(decoder_layer, config=c, parallel=parallel,
                                 mesh=None, use_flash=use_flash,
                                 tp_axis=tp_axis, in_shard_map=sep_on)
        def scan_body(hh, p):
            return body(p, hh, cos, sin), None
        if parallel.remat:
            scan_body = jax.checkpoint(scan_body, policy=_remat_policy(parallel))
        h, _ = lax.scan(scan_body, h, stage_params)
        return h

    def pipelined_loss(p, ids, labels):
        # inside shard_map: manual over 'pp' (and batch axes for psums).
        # With sep>1 ids/labels arrive sequence-sharded: [B, S_local].
        b, s = ids.shape
        s_total = s * (parallel.sep if sep_on else 1)
        cos, sin = build_rope_cache(s_total, c.head_dim, base=c.rope_theta)
        if sep_on:
            idx = lax.axis_index("sep") * s
            cos = lax.dynamic_slice_in_dim(cos, idx, s, 0)
            sin = lax.dynamic_slice_in_dim(sin, idx, s, 0)
        h = jnp.take(p["embed"], ids, axis=0).astype(c.dtype)
        from ..parallel.pipeline import microbatch, pipeline_apply, last_stage_value
        h_mb = microbatch(h, M)

        pipe = pipeline_apply(
            lambda sp, hh: stage_fn(sp, hh, cos, sin), S, M, "pp",
            remat=False,  # remat already inside stage scan
            overlap_p2p=parallel.overlap_p2p)
        out_mb = pipe(p["layers"], h_mb)
        h_out = out_mb.reshape(b, s, c.hidden_size)
        logits = llama_logits(p, h_out, c).astype(jnp.float32)
        loss = masked_ce_loss(logits, labels, psum_axes=loss_psum_axes)
        return last_stage_value(loss, S, "pp")

    # FULLY manual island: 'pp' (ppermute schedule), 'mp' (explicit Megatron
    # psums), 'sep' (ring attention's ppermute), AND the batch axes. Mixing
    # manual and auto axes fails twice over: auto mp/sep collectives crash
    # XLA's SPMD group expansion (spmd_partitioner_util CHECK at 32 devices),
    # and ANY leftover auto axis makes lax.axis_index lower to a PartitionId
    # instruction the SPMD partitioner rejects as UNIMPLEMENTED. The batch
    # axes are handled like the sep path above: ids/labels enter batch-
    # sharded and masked_ce_loss psums token sum/count across them.
    manual_axes = frozenset(mesh.axis_names)

    def manual_spec(full_spec, lead_pp: bool):
        parts = ["pp"] if lead_pp else []
        for ax in (tuple(full_spec)[1:] if lead_pp else tuple(full_spec)):
            parts.append(ax if (ax == "mp" and tp_axis) else None)
        return P(*parts)

    pp_manual = jax.tree_util.tree_map(
        lambda s: manual_spec(s, lead_pp=False), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    pp_manual["layers"] = jax.tree_util.tree_map(
        lambda s: manual_spec(s, lead_pp=True), pspecs["layers"],
        is_leaf=lambda x: isinstance(x, P))
    # embed/final_norm/lm_head compute replicated across mp in the manual
    # region (their heavy math is outside the layer stack)
    pp_manual["embed"] = P()
    pp_manual["final_norm"] = P()
    if "lm_head" in pp_manual:
        pp_manual["lm_head"] = P()
    ids_spec = P(batch_axes, "sep" if sep_on else None)
    in_specs = (pp_manual, ids_spec, ids_spec)
    smap_loss = shard_map(pipelined_loss, mesh=mesh, in_specs=in_specs,
                          out_specs=P(), axis_names=manual_axes,
                          check_vma=False)

    def step(p, opt, ids, labels):
        def island(pp_, i, l):
            with _obs.comm_span("llama.pp_island",
                                nbytes=i.size * i.dtype.itemsize,
                                site="llama.pp_island"):
                return smap_loss(pp_, i, l)
        loss, grads = jax.value_and_grad(island)(p, ids, labels)
        new_p, new_opt = _adamw_update(p, grads, opt, lr)
        return new_p, new_opt, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    batch_sharding = NamedSharding(
        mesh, P(batch_axes, "sep" if sep_on else None))

    def step_fn(p, opt, ids, labels):
        ids = jax.device_put(jnp.asarray(ids, jnp.int32), batch_sharding)
        labels = jax.device_put(jnp.asarray(labels, jnp.int32), batch_sharding)
        return jit_step(p, opt, ids, labels)

    return step_fn, params, opt_state


def count_params(config: LlamaConfig) -> int:
    c = config
    per_layer = (c.hidden_size * (c.num_attention_heads +
                                  2 * c.num_key_value_heads) * c.head_dim
                 + c.num_attention_heads * c.head_dim * c.hidden_size
                 + 3 * c.hidden_size * c.intermediate_size
                 + 2 * c.hidden_size)
    total = c.num_hidden_layers * per_layer + c.vocab_size * c.hidden_size \
        + c.hidden_size
    if not c.tie_word_embeddings:
        total += c.hidden_size * c.vocab_size
    return total


def train_flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """~6N + attention flops per token (fwd+bwd), for MFU accounting."""
    n = count_params(config)
    attn = 12 * config.num_hidden_layers * config.hidden_size * seq_len
    return 6.0 * n + attn


def beam_search_scan(params, cache, first_logits, num_tokens, config,
                     num_beams, length_penalty=0.0, eos_token_id=None):
    """Beam search INSIDE one jit (ref: the reference's BeamSearchDecoder /
    generation beam_search): beams ride the batch dim (B*K rows), the KV
    cache is gathered to each step's surviving parents, and the token/
    parent history is emitted per step and assembled by the gather_tree
    backtrack at the end. Returns (sequences [B, K, num_tokens], scores
    [B, K]) sorted best-first per batch row.

    first_logits: [B, V] prefill logits. cache: prefilled for B rows;
    expanded to B*K here. eos_token_id: finished beams are extended only
    with EOS at zero extra cost and their score frozen (length_penalty
    applies as score / (len ** penalty), GNMT-style, at the end)."""
    b, v = first_logits.shape
    k = num_beams
    neg = jnp.float32(-1e9)

    # seed: top-k tokens of the prefill logits start the k beams
    logp0 = jax.nn.log_softmax(first_logits.astype(jnp.float32), axis=-1)
    cum, tok0 = lax.top_k(logp0, k)                      # [B, K] each
    # expand cache to B*K rows (beam-major within each batch row)
    def tile(a):
        return jnp.repeat(a, k, axis=1)
    cache = {"k": tile(cache["k"]), "v": tile(cache["v"]),
             "pos": cache["pos"]}

    def step(carry, _):
        cache, cum, tok, alive_len = carry
        logits, cache = llama_decode_step(
            params, cache, tok.reshape(b * k, 1).astype(jnp.int32), config)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, v)
        if eos_token_id is not None:
            finished = tok == eos_token_id                 # [B, K]
            # finished beams: only EOS continues, at no cost
            only_eos = jnp.full((v,), neg).at[eos_token_id].set(0.0)
            logp = jnp.where(finished[..., None], only_eos[None, None], logp)
            alive_len = alive_len + (~finished)
        else:
            alive_len = alive_len + 1
        total = cum[..., None] + logp                      # [B, K, V]
        cum, flat = lax.top_k(total.reshape(b, k * v), k)  # [B, K]
        parent = (flat // v).astype(jnp.int32)             # [B, K]
        tok = (flat % v).astype(jnp.int32)
        # gather cache rows to the surviving parents
        rows = (jnp.arange(b, dtype=jnp.int32)[:, None] * k
                + parent).reshape(-1)
        cache = {"k": jnp.take(cache["k"], rows, axis=1),
                 "v": jnp.take(cache["v"], rows, axis=1),
                 "pos": cache["pos"]}
        alive_len = jnp.take_along_axis(alive_len, parent, axis=1)
        return (cache, cum, tok, alive_len), (tok, parent)

    alive0 = jnp.ones((b, k), jnp.int32)
    (cache, cum, _, alive_len), (toks, parents) = lax.scan(
        step, (cache, cum, tok0.astype(jnp.int32), alive0),
        None, length=num_tokens - 1)

    # assemble: history [T, B, K]; step 0's parents are the identity
    all_toks = jnp.concatenate([tok0.astype(jnp.int32)[None], toks], 0)
    id0 = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, None],
                           (1, b, k))
    all_parents = jnp.concatenate([id0, parents], 0)
    from ..nn.functional.common import _gather_tree_impl
    seqs = _gather_tree_impl(all_toks, all_parents)        # [T, B, K]
    scores = cum / jnp.maximum(alive_len.astype(jnp.float32),
                               1.0) ** length_penalty
    # re-sort: the scan keeps beams ordered by raw cumulative logprob, but
    # the length penalty can reorder them (short finished vs long alive)
    order = jnp.argsort(-scores, axis=-1)
    scores = jnp.take_along_axis(scores, order, axis=-1)
    seqs = jnp.transpose(seqs, (1, 2, 0))                  # [B, K, T]
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    return seqs, scores


def beam_search_generate(params, prompt_ids, config: LlamaConfig,
                         max_new_tokens, num_beams=4, length_penalty=0.0,
                         eos_token_id=None, max_len=None):
    """Beam-search generation: prefill once, then the whole search is a
    single compiled scan. Returns (sequences [B, num_beams,
    max_new_tokens], scores [B, num_beams]) best-first."""
    prompt, logits, cache, frozen = _prefill_for_generate(
        params, prompt_ids, config, max_new_tokens, max_len, 0,
        "beam_search_generate")
    if logits is None:
        b = prompt.shape[0]
        return (np.zeros((b, num_beams, 0), np.int32),
                np.zeros((b, num_beams), np.float32))
    # NO pow2 bucketing here: beam scores are sums over the emitted
    # sequence, so extra padded steps would change both scores and which
    # beams survive — each max_new_tokens compiles exactly
    seqs, scores = _jitted_beam(frozen, int(max_new_tokens),
                                int(num_beams), float(length_penalty),
                                eos_token_id)(params, cache, logits)
    return np.asarray(seqs), np.asarray(scores)


@functools.lru_cache(maxsize=32)
def _jitted_beam(frozen, num_tokens, num_beams, length_penalty,
                 eos_token_id):
    config = LlamaConfig(*frozen)

    def beam_scan_fn(params, cache, first_logits):
        return beam_search_scan(params, cache, first_logits, num_tokens,
                                config, num_beams, length_penalty,
                                eos_token_id)
    beam_scan_fn.__name__ = "beam_scan"
    # no donation: the cache is re-tiled to B*K rows inside the jit, so no
    # output matches the donated buffer (donating only warns uselessly)
    return jax.jit(beam_scan_fn)


def generate(params, prompt_ids, config: LlamaConfig, max_new_tokens=64,
             decode_strategy="greedy_search", temperature=1.0, top_k=0,
             top_p=1.0, num_beams=4, length_penalty=0.0, eos_token_id=None,
             seed=0, max_len=None):
    """Unified generation entry (ref: the reference generate API's
    decode_strategy dispatch): 'greedy_search' | 'sampling' |
    'beam_search'. Greedy/sampling return [B, max_new_tokens] token ids;
    beam search returns the best beam per batch row (use
    beam_search_generate directly for all beams + scores).
    eos_token_id is supported by the beam path only (the greedy/sampling
    scans have a fixed trip count) — passing it elsewhere raises rather
    than silently generating past EOS."""
    if eos_token_id is not None and decode_strategy != "beam_search":
        raise ValueError(
            "eos_token_id is only supported with "
            "decode_strategy='beam_search'")
    if decode_strategy == "greedy_search":
        return greedy_generate(params, prompt_ids, config, max_new_tokens,
                               max_len=max_len)
    if decode_strategy == "sampling":
        return sample_generate(params, prompt_ids, config, max_new_tokens,
                               temperature=temperature, top_k=top_k,
                               top_p=top_p, seed=seed, max_len=max_len)
    if decode_strategy == "beam_search":
        seqs, _ = beam_search_generate(params, prompt_ids, config,
                                       max_new_tokens, num_beams=num_beams,
                                       length_penalty=length_penalty,
                                       eos_token_id=eos_token_id,
                                       max_len=max_len)
        return seqs[:, 0]
    raise ValueError(
        f"unknown decode_strategy {decode_strategy!r}; expected "
        "'greedy_search', 'sampling', or 'beam_search'")
