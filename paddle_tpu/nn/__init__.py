"""paddle_tpu.nn: module system + layers (ref: python/paddle/nn/)."""
from . import functional
from . import initializer
from . import utils
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   clip_grad_norm_)
from .layer.activation import (CELU, ELU, GELU, GLU, SELU, Hardshrink,
                               Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
                               LogSigmoid, LogSoftmax, Maxout, Mish, PReLU,
                               ReLU, ReLU6, RReLU, Sigmoid, Silu, Softmax, Softmax2D,
                               Softplus, Softshrink, Softsign, Swish, Tanh,
                               Tanhshrink, ThresholdedReLU)
from .layer.common import (AlphaDropout, FeatureAlphaDropout,
                           Threshold, Bilinear, ChannelShuffle,
                           CosineSimilarity, Dropout, Dropout2D, Dropout3D,
                           Embedding, Flatten, Fold, Identity, Linear, Pad1D,
                           Pad2D, Pad3D, PairwiseDistance, PixelShuffle,
                           PixelUnshuffle, Unflatten, Unfold, Upsample,
                           UpsamplingBilinear2D, UpsamplingNearest2D,
                           ZeroPad2D)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,
                         Conv3D, Conv3DTranspose)
from .layer.layers import Layer, ParamAttr, Parameter
from .layer.loss import (AdaptiveLogSoftmaxWithLoss, BCELoss,
                         BCEWithLogitsLoss, CosineEmbeddingLoss,
                         CrossEntropyLoss, CTCLoss, GaussianNLLLoss,
                         HingeEmbeddingLoss, HuberLoss, KLDivLoss, L1Loss,
                         MarginRankingLoss, MSELoss, MultiLabelSoftMarginLoss,
                         MultiMarginLoss, NLLLoss, PoissonNLLLoss, RNNTLoss,
                         SmoothL1Loss, SoftMarginLoss, TripletMarginLoss,
                         TripletMarginWithDistanceLoss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm, RMSNorm,
                         SpectralNorm, SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                            AdaptiveAvgPool3D, AdaptiveMaxPool2D, AvgPool1D,
                            AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                            MaxPool3D, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
                            LPPool1D, LPPool2D)
from .layer.rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, SimpleRNN,
                        SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
from . import quant  # noqa: F401
