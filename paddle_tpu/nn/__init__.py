"""paddle_tpu.nn: module system + layers (ref: python/paddle/nn/)."""
from . import functional
from . import initializer
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   clip_grad_norm_)
from .layer.activation import (CELU, ELU, GELU, GLU, SELU, Hardshrink,
                               Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
                               LogSigmoid, LogSoftmax, Maxout, Mish, PReLU,
                               ReLU, ReLU6, RReLU, Sigmoid, Silu, Softmax,
                               Softplus, Softshrink, Softsign, Swish, Tanh,
                               Tanhshrink, ThresholdedReLU)
from .layer.common import (AlphaDropout, Bilinear, CosineSimilarity, Dropout,
                           Dropout2D, Embedding, Flatten, Identity, Linear,
                           Pad2D, PixelShuffle, Unflatten, Upsample)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,
                         Conv3D, Conv3DTranspose)
from .layer.layers import Layer, ParamAttr, Parameter
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, CTCLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         NLLLoss, PoissonNLLLoss, SmoothL1Loss,
                         TripletMarginLoss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm, RMSNorm,
                         SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                            AdaptiveAvgPool3D, AdaptiveMaxPool2D, AvgPool1D,
                            AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                            MaxPool3D)
from .layer.rnn import (GRU, LSTM, RNN, GRUCell, LSTMCell, SimpleRNN,
                        SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
