"""Gradient clipping (ref: python/paddle/nn/clip.py).

ClipGradByGlobalNorm computes the norm in fp32 over all grads; under hybrid
parallel, HybridParallelClipGrad (distributed/fleet) extends this with psums
over mesh axes.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor._from_data(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor._from_data((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm_sq(self, params_grads):
        total = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            total = total + jnp.sum(jnp.square(g._data.astype(jnp.float32)))
        return total

    def __call__(self, params_grads):
        total = self._global_norm_sq(params_grads)
        global_norm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor._from_data((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type) for g in grads]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor._from_data(total)
