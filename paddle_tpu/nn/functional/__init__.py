"""Functional ops (ref: python/paddle/nn/functional/).

All compute lowers to jnp/lax so XLA fuses elementwise chains into the
surrounding matmuls/convs; scaled_dot_product_attention routes to the Pallas
flash-attention kernel on TPU (ops/flash_attention.py).
"""
from .activation import (relu, relu6, relu_, gelu, silu, swish, sigmoid,
                         log_sigmoid, tanh, softmax, log_softmax, softplus,
                         softsign, leaky_relu, elu, selu, celu, hardshrink,
                         hardsigmoid, hardswish, hardtanh, mish, prelu,
                         rrelu, tanhshrink, softshrink, thresholded_relu,
                         maxout, glu, gumbel_softmax)
from .common import (linear, dropout, dropout2d, dropout3d, embedding,
                     one_hot, pad, interpolate, upsample, unfold, fold,
                     pixel_shuffle, cosine_similarity, pairwise_distance,
                     label_smooth, bilinear, alpha_dropout, sequence_mask,
                     threshold, zeropad2d,
                     feature_alpha_dropout, gather_tree,
                     sparse_attention)
from .vision import (affine_grid, grid_sample, pixel_unshuffle,
                     channel_shuffle, temporal_shift)
from .conv import conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose, conv3d_transpose
from .pooling import (avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d,
                      max_pool2d, max_pool3d, adaptive_avg_pool1d,
                      adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool1d, adaptive_max_pool2d, global_avg_pool2d,
                      max_unpool1d, max_unpool2d, max_unpool3d,
                      lp_pool1d, lp_pool2d)
from .norm import (layer_norm, batch_norm, instance_norm, group_norm,
                   rms_norm, local_response_norm, normalize)
from .loss import margin_cross_entropy, class_center_sample  # noqa
from .loss import (cross_entropy, softmax_with_cross_entropy, mse_loss,
                   l1_loss, nll_loss, binary_cross_entropy,
                   binary_cross_entropy_with_logits, smooth_l1_loss,
                   kl_div, margin_ranking_loss, cosine_embedding_loss,
                   hinge_embedding_loss, square_error_cost, log_loss,
                   sigmoid_focal_loss, ctc_loss, triplet_margin_loss,
                   poisson_nll_loss, gaussian_nll_loss, soft_margin_loss,
                   multi_label_soft_margin_loss, multi_margin_loss,
                   dice_loss, npair_loss, rnnt_loss,
                   adaptive_log_softmax_with_loss, hsigmoid_loss,
                   triplet_margin_with_distance_loss)
from .attention import (flash_attention, flash_attn_unpadded,
                        scaled_dot_product_attention, sdp_kernel)
