"""Activation functions (ref: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, _run_op
from ...framework import random as random_mod


def _act(name, jfn):
    def op(x, name=None):
        return _run_op(name, jfn, (x,), {})
    op.__name__ = name
    return op


relu = _act("relu", lambda a: jax.nn.relu(a))
relu6 = _act("relu6", lambda a: jnp.clip(a, 0, 6))
sigmoid = _act("sigmoid", lambda a: jax.nn.sigmoid(a))
log_sigmoid = _act("log_sigmoid", lambda a: jax.nn.log_sigmoid(a))
tanh = _act("tanh", lambda a: jnp.tanh(a))
silu = _act("silu", lambda a: jax.nn.silu(a))
swish = silu
softplus_ = None
softsign = _act("softsign", lambda a: jax.nn.soft_sign(a))
mish = _act("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
tanhshrink = _act("tanhshrink", lambda a: a - jnp.tanh(a))


def relu_(x):
    x._data = jax.nn.relu(x._data)
    x._grad_node = None
    return x


def gelu(x, approximate=False, name=None):
    return _run_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), (x,), {})


def softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...framework import dtype as dm
            a = a.astype(dm.convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return _run_op("softmax", f, (x,), {})


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _run_op("log_softmax", lambda a: jax.nn.log_softmax(a, axis=axis), (x,), {})


def softplus(x, beta=1, threshold=20, name=None):
    def f(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jnp.log1p(jnp.exp(scaled)) / beta)
    return _run_op("softplus", f, (x,), {})


def leaky_relu(x, negative_slope=0.01, name=None):
    return _run_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), (x,), {})


def elu(x, alpha=1.0, name=None):
    return _run_op("elu", lambda a: jax.nn.elu(a, alpha), (x,), {})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _run_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), (x,), {})


def celu(x, alpha=1.0, name=None):
    return _run_op("celu", lambda a: jax.nn.celu(a, alpha), (x,), {})


def hardshrink(x, threshold=0.5, name=None):
    return _run_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), (x,), {})


def softshrink(x, threshold=0.5, name=None):
    def f(a):
        return jnp.where(a > threshold, a - threshold,
                         jnp.where(a < -threshold, a + threshold, 0.0))
    return _run_op("softshrink", f, (x,), {})


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _run_op("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), (x,), {})


def hardswish(x, name=None):
    return _run_op("hardswish", lambda a: a * jnp.clip(a + 3, 0, 6) / 6, (x,), {})


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _run_op("hardtanh", lambda a: jnp.clip(a, min, max), (x,), {})


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)
    return _run_op("prelu", f, (x, weight), {})


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        k = random_mod.next_key()
        def f(a):
            slope = jax.random.uniform(k, a.shape, jnp.float32, lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)
        return _run_op("rrelu", f, (x,), {})
    mid = (lower + upper) / 2
    return leaky_relu(x, mid)


def thresholded_relu(x, threshold=1.0, name=None):
    return _run_op("thresholded_relu", lambda a: jnp.where(a > threshold, a, 0.0), (x,), {})


def maxout(x, groups, axis=1, name=None):
    def f(a):
        c = a.shape[axis]
        new_shape = list(a.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(a.reshape(new_shape), axis=axis + 1)
    return _run_op("maxout", f, (x,), {})


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return _run_op("glu", f, (x,), {})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    k = random_mod.next_key()
    def f(a):
        g = -jnp.log(-jnp.log(jax.random.uniform(k, a.shape, jnp.float32) + 1e-20) + 1e-20)
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y
    return _run_op("gumbel_softmax", f, (x,), {})
