"""Scaled dot-product attention (ref: paddle/phi/kernels/gpu/flash_attn_kernel.cu
+ python/paddle/nn/functional/flash_attention.py).

Layout matches the reference: [batch, seq, num_heads, head_dim]. On TPU the op
routes to the Pallas flash-attention kernel (ops/flash_attention.py); elsewhere
(or when FLAGS_use_pallas_kernels=0) it falls back to the XLA softmax path with
fp32 accumulation.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ...framework import flags
from ...tensor.tensor import Tensor, _run_op


def _xla_sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None):
    # [B, S, H, D] -> compute in [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2)
    hq, hk = qh.shape[1], kh.shape[1]
    if hk != hq:  # GQA: repeat kv heads
        rep = hq // hk
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(jnp.float32(d))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        # ADDITIVE mask, not select: a broadcasted-pred select over
        # sharded logits made GSPMD replicate the operand ("Involuntary
        # full rematerialization" on the select_n in the r4 multichip
        # dryrun); addition partitions elementwise with no resharding
        neg = jnp.triu(jnp.full((sq, sk), -1e30, jnp.float32),
                       k=sk - sq + 1)
        logits = logits + neg
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vh.dtype), vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _use_pallas(query) -> bool:
    if not flags.get_flag("use_pallas_kernels"):
        return False
    data = query._data if isinstance(query, Tensor) else query
    try:
        dev = next(iter(data.devices()))
        return dev.platform != "cpu"
    except Exception:
        # tracer: no concrete device — trust the default backend
        return jax.default_backend() == "tpu"


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    if _use_pallas(query) and attn_mask is None and dropout_p == 0.0:
        from ...ops.flash_attention import flash_attention_bshd
        def f(q, k, v):
            return flash_attention_bshd(q, k, v, causal=is_causal, scale=scale)
        return _run_op("flash_attention", f, (query, key, value), {})
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    def f(q, k, v, *m):
        return _xla_sdpa(q, k, v, m[0] if m else None, dropout_p, is_causal, scale)
    return _run_op("sdpa", f, args, {})


@contextlib.contextmanager
def sdp_kernel(enable_flash=True, enable_math=True, enable_mem_efficient=True):
    prev = flags.get_flag("use_pallas_kernels")
    flags.set_flags({"use_pallas_kernels": enable_flash})
    try:
        yield
    finally:
        flags.set_flags({"use_pallas_kernels": prev})


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, name=None):
    """paddle.nn.functional.flash_attention parity wrapper."""
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    if return_softmax:
        return out, None
    return out, None


def _varlen_attention(q, k, v, cu_q, cu_k, max_q, max_k, scale, causal):
    """Packed varlen attention core: q [total_q, H, D], k/v [total_k, Hkv, D],
    cu_* are [B+1] cumulative sequence offsets. Returns [total_q, H, D].

    TPU shape strategy: scatter the packed tokens into a padded [B, max, H, D]
    batch (static shapes for XLA), run masked attention with fp32 logits, and
    gather the valid rows back to the packed layout. Fully-padded rows never
    reach the output gather, so no gradient flows through them. O(B*max_q*
    max_k) logits — the flash-kernel segment-mask route is the upgrade path
    for long packed batches."""
    B = cu_q.shape[0] - 1
    lens_q = cu_q[1:] - cu_q[:-1]
    lens_k = cu_k[1:] - cu_k[:-1]
    iq = jnp.arange(max_q)
    ik = jnp.arange(max_k)
    idx_q = jnp.clip(cu_q[:-1, None] + iq[None], 0, q.shape[0] - 1)
    idx_k = jnp.clip(cu_k[:-1, None] + ik[None], 0, k.shape[0] - 1)
    valid_q = iq[None] < lens_q[:, None]                      # [B, max_q]
    valid_k = ik[None] < lens_k[:, None]                      # [B, max_k]
    qp = jnp.take(q, idx_q, axis=0)                           # [B,max_q,H,D]
    kp = jnp.take(k, idx_k, axis=0)
    vp = jnp.take(v, idx_k, axis=0)
    mask = valid_q[:, None, :, None] & valid_k[:, None, None, :]
    if causal:
        # per-sequence top-left causal (reference semantics): query position
        # i within its sequence attends key positions <= i
        mask = mask & (iq[:, None] >= ik[None, :])[None, None]
    out = _xla_sdpa(qp, kp, vp, attn_mask=mask, scale=scale)  # [B,max_q,H,D]
    t = jnp.arange(q.shape[0])
    seg = jnp.searchsorted(cu_q, t, side="right") - 1
    src = seg * max_q + (t - cu_q[seg])
    flat = out.reshape(B * max_q, *out.shape[2:])
    return jnp.take(flat, src, axis=0).astype(q.dtype)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (unpadded) attention over packed sequences (ref:
    python/paddle/nn/functional/flash_attention.py flash_attn_unpadded).

    query: [total_q, num_heads, head_dim] — all sequences concatenated;
    cu_seqlens_q/k: [batch+1] int32 cumulative offsets (cu[0]=0,
    cu[-1]=total). Returns (out [total_q, H, D], softmax=None).

    On TPU (and within the segment-code limits) this runs the Pallas
    streaming flash kernels directly on the PACKED layout — O(total * D)
    memory, no [B, max_q, max_k] logits; elsewhere it falls back to the
    padded-batch XLA path (_varlen_attention)."""
    max_q, max_k = int(max_seqlen_q), int(max_seqlen_k)

    n_seqs = cu_seqlens_q.shape[0] - 1
    use_kernel = (_use_pallas(query) and dropout == 0.0
                  and n_seqs < 1024 and max(max_q, max_k) < (1 << 20))
    if use_kernel:
        from ...ops.flash_varlen import flash_varlen_attention
        self_attn = cu_seqlens_q is cu_seqlens_k

        def fk(q, k, v, cq, ck):
            s = (1.0 / float(q.shape[-1]) ** 0.5) if scale is None else scale
            return flash_varlen_attention(q, k, v, cq, ck, s, causal,
                                          self_attn=self_attn,
                                          max_seqlen=max(max_q, max_k))

        out = _run_op("flash_attn_unpadded", fk,
                      (query, key, value, cu_seqlens_q, cu_seqlens_k), {})
        return out, None

    def f(q, k, v, cq, ck):
        if scale is None:
            s = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
        else:
            s = scale
        return _varlen_attention(q, k, v, cq.astype(jnp.int32),
                                 ck.astype(jnp.int32), max_q, max_k, s,
                                 causal)

    out = _run_op("flash_attn_unpadded", f,
                  (query, key, value, cu_seqlens_q, cu_seqlens_k), {})
    if return_softmax:
        return out, None
    return out, None
