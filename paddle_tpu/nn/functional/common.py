"""Common functional ops: linear, dropout, embedding, padding, etc.
(ref: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...amp import state as amp_state
from ...framework import random as random_mod
from ...tensor.tensor import Tensor, _run_op
from ...tensor import manipulation as manip


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. W layout [in, out] like the reference; bf16 under AMP
    so XLA maps it onto the MXU."""
    if bias is None:
        def f(a, w):
            a, w = amp_state.maybe_autocast_pair(a, w)
            return jnp.matmul(a, w)
        return _run_op("linear", f, (x, weight), {})
    def f(a, w, b):
        a, w = amp_state.maybe_autocast_pair(a, w)
        return jnp.matmul(a, w) + b.astype(a.dtype if amp_state.autocast_enabled() else b.dtype)
    return _run_op("linear", f, (x, weight, bias), {})


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = random_mod.next_key()
    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return _run_op("dropout", f, (x,), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = random_mod.next_key()
    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        # var after masking = (1-p)*(1 + p*alpha_p^2): normalize back to 1
        a_coef = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return _run_op("alpha_dropout", f, (x,), {})


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return _run_op("embedding", f, (x, weight), {})


def one_hot(x, num_classes, name=None):
    return _run_op("one_hot",
                   lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes, dtype=jnp.float32),
                   (x,), {})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return manip.pad(x, pad, mode=mode, value=value, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    def f(a):
        nchw = data_format == "NCHW"
        spatial = a.shape[2:] if nchw else a.shape[1:-1]
        if size is not None:
            tgt = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in
                        (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            tgt = tuple(int(s * f_) for s, f_ in zip(spatial, sf))
        if nchw:
            out_shape = a.shape[:2] + tgt
        else:
            out_shape = (a.shape[0],) + tgt + (a.shape[-1],)
        method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                  "bicubic": "cubic", "trilinear": "linear", "area": "linear"}[mode]
        return jax.image.resize(a, out_shape, method=method).astype(a.dtype)
    return _run_op("interpolate", f, (x,), {})


upsample = interpolate


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return _run_op("unfold", f, (x,), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    def f(a):
        n, ckk, l = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0]: pd[0] + os_[0], pd[1]: pd[1] + os_[1]]
    return _run_op("fold", f, (x,), {})


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)
    return _run_op("pixel_shuffle", f, (x,), {})


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)
    return _run_op("cosine_similarity", f, (x1, x2), {})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return _run_op("pairwise_distance", f, (x, y), {})


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return _run_op("label_smooth", f, (label,), {})


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bias_arg):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bias_arg:
            out = out + bias_arg[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return _run_op("bilinear", f, args, {})


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Row-wise [0,len) masks (ref: paddle.nn.functional.sequence_mask).

    With maxlen=None the max length is resolved eagerly at call time (host
    sync) so the captured op stays shape-static under jit replay.
    """
    from ...framework import dtype as dtype_mod
    nd = dtype_mod.convert_dtype(dtype)
    if maxlen is None:
        import numpy as _np
        maxlen = int(_np.asarray(
            x.numpy() if isinstance(x, Tensor) else x).max())
    m = int(maxlen)
    def f(lens):
        rng = jnp.arange(m)
        return (rng[None, :] < lens.astype(jnp.int64)[..., None]).astype(nd)
    return _run_op("sequence_mask", f, (x,), {})


def threshold(x, threshold=1.0, value=0.0, name=None):
    """x where x > threshold else value (ref: activation.py thresholded
    relu generalization used by nn.Threshold)."""
    def f(a):
        return jnp.where(a > threshold, a, jnp.asarray(value, a.dtype))
    return _run_op("threshold", f, (x,), {})



def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W (ref: common.py zeropad2d; padding is [l, r, t, b])."""
    l, r, t, b = (int(v) for v in padding)
    def f(a):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (t, b), (l, r)]
        else:
            cfg = [(0, 0), (t, b), (l, r), (0, 0)]
        return jnp.pad(a, cfg)
    return _run_op("zeropad2d", f, (x,), {})


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout zeroing whole channels (dim 1), SELU-compatible
    statistics (ref: common.py feature_alpha_dropout)."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = random_mod.next_key()

    def f(a):
        mshape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, mshape)
        a_coef = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return _run_op("feature_alpha_dropout", f, (x,), {})


def _gather_tree_impl(ids_, par_):
    """Raw backtrack on jnp arrays [T, B, K] (shared with the in-jit beam
    search in models/llama.py)."""
    t, b, k = ids_.shape
    from jax import lax

    def step(beam_idx, inputs):
        id_t, par_t = inputs                 # [B, K] each
        out = jnp.take_along_axis(id_t, beam_idx, axis=1)
        nxt = jnp.take_along_axis(par_t, beam_idx, axis=1)
        return nxt.astype(beam_idx.dtype), out

    init = jnp.broadcast_to(jnp.arange(k, dtype=ids_.dtype)[None], (b, k))
    _, outs = lax.scan(step, init, (ids_, par_.astype(ids_.dtype)),
                       reverse=True)
    return outs                              # [T, B, K]


def gather_tree(ids, parents, name=None):
    """Beam-search backtrack (ref: paddle.nn.functional.gather_tree):
    ids/parents [max_time, batch, beam]; walking parent pointers from the
    last step yields the full sequence per surviving beam."""
    return _run_op("gather_tree", _gather_tree_impl, (ids, parents), {})


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with a CSR connectivity pattern (ref:
    sparse_attention.py — a GPU-only custom op there). TPU-native
    substitution: the CSR pattern densifies into a [B, H, S, S] boolean
    mask and runs masked sdpa — correct for any pattern; for the LONG-
    sequence patterns this op exists for, prefer the packed varlen flash
    kernel (ops/flash_varlen.py) or ring attention, which never build the
    dense mask. q/k/v: [B, H, S, D]; offsets [B, H, S+1]; columns
    [B, H, nnz]. Returns [B, H, S, D]."""
    has_kpm = key_padding_mask is not None
    has_am = attn_mask is not None

    def f(q, k, v, off, cols, *masks):
        b, h, s, d = q.shape
        off = off.astype(jnp.int32)
        cols = cols.astype(jnp.int32)
        nnz = cols.shape[-1]
        # row id of nnz entry j = count of row-end offsets <= j
        idx = jnp.arange(nnz)
        rows = (off[..., 1:, None] <= idx[None, None, None, :]).sum(2)
        dense = jnp.zeros((b, h, s, s), bool)
        bi = jnp.arange(b)[:, None, None]
        hi = jnp.arange(h)[None, :, None]
        valid = idx[None, None, :] < off[..., -1:]
        dense = dense.at[bi, hi, rows, cols].set(valid)
        logits = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / jnp.sqrt(float(d))
        mi = 0
        if has_kpm:
            dense = dense & (masks[mi][:, None, None, :] != 0)
            mi += 1
        if has_am:
            am = masks[mi]
            dense = dense & ((am[:, None] if am.ndim == 3 else am) != 0)
        logits = jnp.where(dense, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        # fully-masked rows: reference returns zeros
        any_ok = dense.any(-1, keepdims=True)
        p = jnp.where(any_ok, p, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", p.astype(v.dtype), v)

    extra = tuple(m for m in (key_padding_mask, attn_mask) if m is not None)
    return _run_op("sparse_attention", f,
                   (query, key, value, sparse_csr_offset,
                    sparse_csr_columns) + extra, {})
