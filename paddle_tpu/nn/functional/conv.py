"""Convolutions via lax.conv_general_dilated (ref: python/paddle/nn/functional/conv.py
+ paddle/phi/kernels/gpu/conv_kernel.cu — here XLA owns algorithm selection and
layout assignment on TPU instead of cuDNN).

Weight layout matches the reference: [out_c, in_c/groups, *kernel]. AMP casts
inputs to bf16 so convs hit the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...amp import state as amp_state
from ...tensor.tensor import Tensor, _run_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format,
          name="conv"):
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    chan_last = data_format in ("NHWC", "NLC", "NDHWC")
    spatial = "DHW"[3 - n:] if n <= 3 else None
    if chan_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    out_spec = lhs_spec
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs_spec, rhs_spec, out_spec))

    def f(a, w, *b):
        a, w = amp_state.maybe_autocast_pair(a, w)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[out_spec.index("C")] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape).astype(out.dtype)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return _run_op(name, f, args, {})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NLC" if data_format == "NLC" else "NCL"
    # map 1d onto the generic path with spatial dim "W"
    stride = _tuple(stride, 1)
    dilation = _tuple(dilation, 1)
    pad = _padding(padding, 1)
    chan_last = df == "NLC"
    lhs = "NWC" if chan_last else "NCW"
    dn = jax.lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), (lhs, "OIW", lhs))

    def f(a, w, *b):
        a, w = amp_state.maybe_autocast_pair(a, w)
        out = jax.lax.conv_general_dilated(a, w, stride, pad,
                                           rhs_dilation=dilation,
                                           dimension_numbers=dn,
                                           feature_group_count=groups)
        if b:
            shape = [1, 1, 1]
            shape[lhs.index("C")] = b[0].shape[0]
            out = out + b[0].reshape(shape).astype(out.dtype)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return _run_op("conv1d", f, args, {})


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, name):
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    opad = _tuple(output_padding, n)
    spatial = "DHW"[3 - n:]
    chan_last = data_format in ("NHWC", "NLC", "NDHWC")
    lhs = ("N" + spatial + "C") if chan_last else ("NC" + spatial)
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs, "OI" + spatial, lhs))
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _padding(padding, n)
        # transposed conv padding: lax uses (k-1)*d - p on each side
        pad = []
        for i, (lo, hi) in enumerate(p):
            k_eff = dilation[i] * (weight.shape[2 + i] - 1)
            pad.append((k_eff - lo, k_eff - hi + opad[i]))

    def f(a, w, *b):
        a, w = amp_state.maybe_autocast_pair(a, w)
        # weight layout in reference: [in_c, out_c/groups, *k] for transpose.
        # Build the equivalent forward kernel: swap I/O (per group) and flip
        # the spatial dims (what the removed transpose_kernel flag did).
        in_c = w.shape[0]
        out_per_g = w.shape[1]
        k_dims = w.shape[2:]
        wg = w.reshape((groups, in_c // groups, out_per_g) + k_dims)
        wg = jnp.swapaxes(wg, 1, 2)  # [g, out/g, in/g, *k]
        w_t = wg.reshape((groups * out_per_g, in_c // groups) + k_dims)
        w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + n)))
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1,) * n, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b:
            shape = [1] * out.ndim
            shape[lhs.index("C")] = b[0].shape[0]
            out = out + b[0].reshape(shape).astype(out.dtype)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return _run_op(name, f, args, {})


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NLC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, "NCHW"[:2] + "W" if df == "NCW" else df,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, "conv3d_transpose")
