"""Loss functions (ref: python/paddle/nn/functional/loss.py).

cross_entropy computes log-softmax + gather in fp32 regardless of input dtype
(bf16-safe), matching the reference's softmax_with_cross_entropy numerics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, _run_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    def f(logits, lbl, *w):
        l32 = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(l32, axis=axis) if use_softmax else jnp.log(l32)
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -(tgt * logp).sum(axis=axis)
        else:
            idx = lbl.astype(jnp.int32)
            squeeze = False
            if idx.ndim == logits.ndim:  # trailing [..., 1] label
                idx = jnp.squeeze(idx, axis=axis)
                squeeze = True
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(logp, safe_idx[..., None], axis=axis)
            picked = jnp.squeeze(picked, axis=axis)
            if label_smoothing > 0:
                k = logits.shape[axis]
                smooth = logp.mean(axis=axis)
                loss = -((1 - label_smoothing) * picked + label_smoothing * smooth)
            else:
                loss = -picked
            mask = (idx != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if w:
                loss = loss * jnp.take(w[0].astype(jnp.float32), safe_idx)
            if reduction == "mean":
                denom = jnp.maximum(mask.sum(), 1)
                if w:
                    denom = jnp.maximum((jnp.take(w[0].astype(jnp.float32), safe_idx) * mask).sum(), 1e-12)
                return loss.sum() / denom
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return _run_op("cross_entropy", f, args, {})


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # reference returns loss with a trailing singleton dim
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax as softmax_fn
        return loss, softmax_fn(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return _run_op("mse_loss",
                   lambda a, b: _reduce(jnp.square(a - b), reduction),
                   (input, label), {})


def l1_loss(input, label, reduction="mean", name=None):
    return _run_op("l1_loss",
                   lambda a, b: _reduce(jnp.abs(a - b), reduction),
                   (input, label), {})


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(logp, lbl, *w):
        idx = lbl.astype(jnp.int32)
        safe = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1).squeeze(-1)
        loss = -picked
        mask = idx != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w:
            wt = jnp.take(w[0], safe)
            loss = loss * wt
            if reduction == "mean":
                return loss.sum() / jnp.maximum((wt * mask).sum(), 1e-12)
        if reduction == "mean":
            return loss.sum() / jnp.maximum(mask.sum(), 1)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return _run_op("nll_loss", f, args, {})


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return _run_op("bce", f, args, {})


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on the y term
        if pw is None:
            loss = jnp.maximum(z32, 0) - z32 * y32 + jnp.log1p(jnp.exp(-jnp.abs(z32)))
        else:
            log_sig = jax.nn.log_sigmoid(z32)
            log_sig_neg = jax.nn.log_sigmoid(-z32)
            loss = -(pw * y32 * log_sig + (1 - y32) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return _run_op("bce_with_logits", f, args, {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta) * delta
        return _reduce(loss, reduction)
    return _run_op("smooth_l1", f, (input, label), {})


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, q):
        if log_target:
            loss = jnp.exp(q) * (q - logp)
        else:
            q32 = jnp.maximum(q.astype(jnp.float32), 1e-12)
            loss = q32 * (jnp.log(q32) - logp)
        if reduction == "batchmean":
            return loss.sum() / logp.shape[0]
        return _reduce(loss, reduction)
    return _run_op("kl_div", f, (input, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return _run_op("margin_ranking", f, (input, other, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def f(a, b, y):
        cos = (a * b).sum(-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return _run_op("cosine_embedding", f, (input1, input2, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return _run_op("hinge_embedding", f, (input, label), {})


def square_error_cost(input, label):
    return _run_op("square_error_cost", lambda a, b: jnp.square(a - b), (input, label), {})


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return _run_op("log_loss", f, (input, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return _run_op("sigmoid_focal", f, args, {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        d_pos = jnp.sum(jnp.abs(a - pos + epsilon) ** p, -1) ** (1 / p)
        d_neg = jnp.sum(jnp.abs(a - neg + epsilon) ** p, -1) ** (1 / p)
        if swap:
            d_pn = jnp.sum(jnp.abs(pos - neg + epsilon) ** p, -1) ** (1 / p)
            d_neg = jnp.minimum(d_neg, d_pn)
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)
    return _run_op("triplet_margin", f, (input, positive, negative), {})


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(z, y):
        if log_input:
            loss = jnp.exp(z) - y * z
        else:
            loss = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return _run_op("poisson_nll", f, (input, label), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    def f(lp, lbl, il, ll):
        # lp: [T, B, C] log-probs (reference layout)
        T, B, C = lp.shape
        lp32 = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        S = 2 * lbl.shape[1] + 1
        # extended label sequence with blanks
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        neg_inf = jnp.float32(-1e30)
        alpha = jnp.full((B, S), neg_inf)
        alpha = alpha.at[:, 0].set(lp32[0, :, blank])
        alpha = alpha.at[:, 1].set(jnp.take_along_axis(lp32[0], ext[:, 1:2], axis=1)[:, 0])

        def step(alpha, lp_t):
            prev1 = alpha
            prev2 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            prev3 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            ext_shift = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], 1)
            allow3 = (ext != blank) & (ext != ext_shift)
            m = jnp.maximum(prev1, prev2)
            m = jnp.where(allow3, jnp.maximum(m, prev3), m)
            m_safe = jnp.maximum(m, neg_inf)
            summed = (jnp.exp(prev1 - m_safe) + jnp.exp(prev2 - m_safe)
                      + jnp.where(allow3, jnp.exp(prev3 - m_safe), 0.0))
            new_alpha = m_safe + jnp.log(summed)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new_alpha + emit, None

        alpha_final, _ = jax.lax.scan(step, alpha, lp32[1:])
        # pick final positions based on label_lengths
        last = 2 * ll.astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha_final, last[:, None], 1)[:, 0]
        a_prev = jnp.take_along_axis(alpha_final, jnp.maximum(last - 1, 0)[:, None], 1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll_total = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
        loss = -ll_total
        if reduction == "mean":
            return (loss / jnp.maximum(ll.astype(jnp.float32), 1)).mean()
        return _reduce(loss, reduction)
    return _run_op("ctc_loss", f, (log_probs, labels, input_lengths, label_lengths), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        v = jnp.maximum(var.astype(jnp.float32), epsilon)
        loss = 0.5 * (jnp.log(v) + (mu - y).astype(jnp.float32) ** 2 / v)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return _run_op("gaussian_nll_loss", f, (input, label, variance), {})


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        # softplus(-y*x) == log1p(exp(-y*x)) but stable for large |x|
        return _reduce(jax.nn.softplus(-y.astype(x.dtype) * x), reduction)
    return _run_op("soft_margin_loss", f, (input, label), {})


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def f(x, y, *w):
        x32, y32 = x.astype(jnp.float32), y.astype(jnp.float32)
        per = -(y32 * jax.nn.log_sigmoid(x32)
                + (1 - y32) * jax.nn.log_sigmoid(-x32))
        if w:
            per = per * w[0]
        return _reduce(per.mean(axis=-1), reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return _run_op("multi_label_soft_margin_loss", f, args, {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def f(x, y, *w):
        n, c = x.shape
        x32 = x.astype(jnp.float32)
        xy = jnp.take_along_axis(x32, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.maximum(0.0, margin - xy + x32) ** p
        if w:
            m = m * jnp.take_along_axis(
                jnp.broadcast_to(w[0], (n, c)), y[:, None].astype(jnp.int32), 1)
        mask = jax.nn.one_hot(y.astype(jnp.int32), c)
        return _reduce((m * (1 - mask)).sum(axis=1) / c, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return _run_op("multi_margin_loss", f, args, {})


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - dice coefficient, label one-hot over the trailing class dim
    (ref: paddle.nn.functional.dice_loss)."""
    def f(x, y):
        c = x.shape[-1]
        yh = jax.nn.one_hot(jnp.squeeze(y, -1).astype(jnp.int32), c,
                            dtype=x.dtype)
        dims = tuple(range(1, x.ndim))
        inter = jnp.sum(x * yh, axis=dims)
        union = jnp.sum(x, axis=dims) + jnp.sum(yh, axis=dims)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))
    return _run_op("dice_loss", f, (input, label), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (ref: paddle.nn.functional.npair_loss)."""
    def f(a, p, y):
        a32, p32 = a.astype(jnp.float32), p.astype(jnp.float32)
        sim = a32 @ p32.T
        same = (y[:, None] == y[None, :]).astype(jnp.float32)
        tgt = same / same.sum(axis=1, keepdims=True)
        xent = -(tgt * jax.nn.log_softmax(sim, axis=1)).sum(1).mean()
        # reference weights the embedding penalty by 0.25 (TF npairs Beta/4)
        reg = l2_reg * 0.25 * (jnp.sum(a32 ** 2) + jnp.sum(p32 ** 2)) / a.shape[0]
        return xent + reg
    return _run_op("npair_loss", f, (anchor, positive, labels), {})


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss: forward-variable DP over the (T, U) lattice as a
    lax.scan over time with an inner scan over label positions
    (ref: paddle.nn.functional.rnnt_loss / warprnnt)."""
    def f(logits, lbl, il, ll):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        b, t_max, u1, _ = lp.shape
        u = u1 - 1
        lbl32 = lbl.astype(jnp.int32)
        emit = jnp.take_along_axis(
            lp[:, :, :u, :], lbl32[:, None, :, None], axis=-1)[..., 0]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148): scale label-emission *gradients*
            # by (1+λ) while leaving the forward loss value unchanged.
            lam = fastemit_lambda
            emit = emit * (1.0 + lam) - jax.lax.stop_gradient(emit * lam)
        blankp = lp[..., blank]                      # (B, T, U+1)

        # t = 0 row: alpha[0, u] = prefix-sum of emissions at t=0
        alpha0 = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.float32),
             jnp.cumsum(emit[:, 0, :], axis=-1)], axis=1)

        def time_step(alpha_prev, t):
            from_blank = alpha_prev + blankp[:, t - 1, :]   # stay at u, t-1 -> t
            e_t = emit[:, t, :]                              # advance u at t

            def u_step(carry, inp):
                fb_u, e_u = inp                              # (B,), (B,)
                val = jnp.logaddexp(fb_u, carry + e_u)
                return val, val
            init = from_blank[:, 0]
            _, rest = jax.lax.scan(
                u_step, init,
                (from_blank[:, 1:].T, e_t.T))
            alpha_t = jnp.concatenate([init[:, None], rest.T], axis=1)
            return alpha_t, alpha_t

        ts = jnp.arange(1, t_max)
        _, alphas = jax.lax.scan(time_step, alpha0, ts)
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T,B,U+1)

        il32 = jnp.clip(il.astype(jnp.int32) - 1, 0, t_max - 1)
        ll32 = jnp.clip(ll.astype(jnp.int32), 0, u)
        final_alpha = all_alphas[il32, jnp.arange(b), ll32]
        final_blank = blankp[jnp.arange(b), il32, ll32]
        loss = -(final_alpha + final_blank)
        if reduction == "mean":
            # reference divides by label length before the batch mean
            return (loss / jnp.maximum(ll.astype(jnp.float32), 1)).mean()
        return _reduce(loss, reduction)
    return _run_op("rnnt_loss", f, (input, label, input_lengths, label_lengths), {})


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (Grave et al.): frequent classes in the head, rare
    classes in down-projected tail clusters
    (ref: paddle.nn.functional.adaptive_log_softmax_with_loss)."""
    def f(x, y, hw, *rest):
        n_clusters = len(cutoffs) - 1
        if head_bias is not None:
            hb = rest[-1]
            tails = rest[:-1]
        else:
            hb = None
            tails = rest
        head_out = x @ hw
        if hb is not None:
            head_out = head_out + hb
        head_lp = jax.nn.log_softmax(head_out.astype(jnp.float32), axis=-1)
        shortlist = cutoffs[0]
        y32 = y.astype(jnp.int32)

        # head part: true class if in shortlist, else its cluster token
        cluster_of = jnp.zeros_like(y32)
        for i in range(n_clusters):
            cluster_of = jnp.where(y32 >= cutoffs[i], i + 1, cluster_of)
        head_idx = jnp.where(y32 < shortlist, y32,
                             shortlist + cluster_of - 1)
        lp = jnp.take_along_axis(head_lp, head_idx[:, None], 1)[:, 0]

        # tail clusters: add in-cluster log prob
        for i in range(n_clusters):
            proj, cls_w = tails[2 * i], tails[2 * i + 1]
            tail_lp = jax.nn.log_softmax(
                ((x @ proj) @ cls_w).astype(jnp.float32), axis=-1)
            local = jnp.clip(y32 - cutoffs[i], 0, cls_w.shape[-1] - 1)
            contrib = jnp.take_along_axis(tail_lp, local[:, None], 1)[:, 0]
            lp = lp + jnp.where(cluster_of == i + 1, contrib, 0.0)
        return lp, -lp.mean()
    tail_flat = tuple(w for pair in tail_weights for w in pair)
    args = (input, label, head_weight) + tail_flat + (
        (head_bias,) if head_bias is not None else ())
    return _run_op("adaptive_log_softmax_with_loss", f, args, {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """triplet_margin_loss with a caller-supplied distance (ref: loss.py).
    distance_function operates on Tensors and defaults to pairwise L2."""
    if distance_function is None:
        from .common import pairwise_distance
        distance_function = pairwise_distance
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        from ...tensor.math import minimum
        d_neg = minimum(d_neg, d_pn)

    def f(dp, dn):
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return _run_op("triplet_margin_dist", f, (d_pos, d_neg), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (ref: loss.py hsigmoid_loss).

    Default tree: the complete binary tree the reference builds without a
    custom dict — leaf c's path is the binary expansion of c + num_classes
    walked down from the root; internal node ids are heap indices - 1
    (root = id 0), num_classes - 1 internal nodes total. Custom trees via
    path_table [N, L] (internal-node ids, padded with -1) and path_code
    [N, L] (0/1 branch codes). weight: [num_classes - 1, D]; bias:
    [num_classes - 1]. Returns [N, 1] (sum of per-node -log sigmoid)."""
    import numpy as np

    if path_table is None:
        max_s = int(np.ceil(np.log2(max(num_classes, 2)))) + 1

        def paths(lbl):
            # leaf heap index = lbl + num_classes; its ancestors are the
            # proper prefixes code >> s (s >= 1, down to the root 1), the
            # branch bit at each is (code >> (s-1)) & 1. Walking bottom-up
            # with a per-level validity mask handles the varying path
            # lengths of a non-power-of-two class count.
            code = lbl + num_classes
            nodes, codes, oks = [], [], []
            for s in range(1, max_s + 1):
                pref = code >> s
                nodes.append(pref - 1)           # node id = heap idx - 1
                codes.append((code >> (s - 1)) & 1)
                oks.append(pref > 0)
            tbl = jnp.stack(nodes, -1)
            cds = jnp.stack(codes, -1)
            ok = jnp.stack(oks, -1)
            return tbl, cds, ok

        def f(x, lbl, w, *b):
            tbl, cds, ok = paths(lbl.reshape(-1).astype(jnp.int32))
            wp = jnp.take(w, jnp.clip(tbl, 0, w.shape[0] - 1), axis=0)
            logits = jnp.einsum("nd,nld->nl", x.astype(jnp.float32),
                                wp.astype(jnp.float32))
            if b:
                logits = logits + jnp.take(b[0], jnp.clip(tbl, 0,
                                                          b[0].shape[0] - 1))
            # reference convention (MatrixBitCodeFunctor): per-node
            # loss = softplus(t) - bit*t: bit 0 -> softplus(t),
            # bit 1 -> softplus(-t) = -log sigmoid(t)
            sgn = 1.0 - 2.0 * cds.astype(jnp.float32)
            lo = jax.nn.softplus(sgn * logits)
            return jnp.where(ok, lo, 0.0).sum(-1, keepdims=True)

        args = (input, label, weight) + ((bias,) if bias is not None else ())
        return _run_op("hsigmoid", f, args, {})

    def f(x, lbl, w, tbl, cds, *b):
        tbl = tbl.astype(jnp.int32)
        ok = tbl >= 0
        wp = jnp.take(w, jnp.clip(tbl, 0, w.shape[0] - 1), axis=0)
        logits = jnp.einsum("nd,nld->nl", x.astype(jnp.float32),
                            wp.astype(jnp.float32))
        if b:
            logits = logits + jnp.take(b[0], jnp.clip(tbl, 0,
                                                      b[0].shape[0] - 1))
        sgn = 1.0 - 2.0 * cds.astype(jnp.float32)
        lo = jax.nn.softplus(sgn * logits)
        return jnp.where(ok, lo, 0.0).sum(-1, keepdims=True)

    args = (input, label, weight, path_table, path_code) + \
        ((bias,) if bias is not None else ())
    return _run_op("hsigmoid_custom", f, args, {})


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (ref: paddle.nn.functional.
    margin_cross_entropy / phi margin_cross_entropy kernel): the target
    class logit cos(theta) is replaced by
    cos(margin1*theta + margin2) - margin3, everything scaled by `scale`.

    Single-controller note: the reference shards classes across model-
    parallel ranks and allreduces the softmax statistics; under GSPMD a
    class-sharded logits array composes the same way via constraint
    specs, so this computes the full formula directly."""
    import jax.numpy as jnp

    from ...tensor.tensor import Tensor, _run_op

    def f(lg, lb):
        lgf = lg.astype(jnp.float32)
        lb_ = lb.reshape(-1)
        cos = jnp.clip(lgf, -1.0, 1.0)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lb_, lg.shape[-1], dtype=jnp.float32)
        adj = jnp.where(onehot > 0, target, cos) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.take_along_axis(logp, lb_[:, None], axis=-1)[:, 0]
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss[:, None]
        return loss_out, jax.nn.softmax(adj, axis=-1)

    import jax
    if return_softmax:
        # one multi-output op: loss and softmax share the forward pass
        return _run_op("margin_cross_entropy", f, (logits, label), {})
    out = _run_op("margin_cross_entropy", lambda a, b: f(a, b)[0],
                  (logits, label), {})
    return out


def class_center_sample(label, num_classes, num_samples, group=None):
    """ref: paddle.nn.functional.class_center_sample (PLSC partial-FC):
    sample `num_samples` class centers — always including every positive
    class in `label` — and remap labels into the sampled index space.
    Returns (remapped_label, sampled_class_center_index).

    Eager host op (like the reference's CPU path): the sampled index set
    has a data-dependent composition; the OUTPUT shapes are static
    (num_samples is the cap, padded with negative-class ids)."""
    import numpy as np

    from ...tensor.tensor import Tensor

    lb = np.asarray(getattr(label, "_data", label)).reshape(-1)
    pos = np.unique(lb)
    # fresh negatives every call (the reference samples per step), seeded
    # from the framework stream so paddle.seed reproduces runs
    from ...framework import random as _random
    rng = np.random.default_rng(
        int(np.asarray(_random.next_key())[-1]))
    if len(pos) > num_samples:
        raise ValueError(
            f"class_center_sample: num_samples={num_samples} is smaller "
            f"than the {len(pos)} distinct positive classes in the batch "
            "— every positive must be kept; raise num_samples")
    if len(pos) == num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos,
                                assume_unique=True)
        extra = rng.choice(neg_pool, size=num_samples - len(pos),
                           replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    import jax.numpy as jnp
    return (Tensor(jnp.asarray(remap[lb])),
            Tensor(jnp.asarray(sampled.astype(np.int64))))
