"""Normalization functional ops (ref: python/paddle/nn/functional/norm.py).

rms_norm is a first-class op (the reference implements it as a fused CUDA
kernel, paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu); here the default
path is jnp (XLA fuses it) with a Pallas kernel override on TPU for long rows
(ops/rms_norm.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, _run_op


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    def f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return _run_op("layer_norm", f, args, {})


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm: x * w / sqrt(mean(x^2)). fp32 accumulation, compute dtype out."""
    def f(a, *w):
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(a32), axis=-1, keepdims=True)
        out = a32 * jax.lax.rsqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    args = (x,) + ((weight,) if weight is not None else ())
    return _run_op("rms_norm", f, args, {})


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Batch norm with reference semantics: running stats are updated in place
    on the mean/var tensors during training (the eager path); the jit path
    captures buffer updates via jit/functional.py's buffer swap."""
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        def stats(a):
            a32 = a.astype(jnp.float32)
            m = jnp.mean(a32, axis=reduce_axes)
            v = jnp.var(a32, axis=reduce_axes)
            return m, v
        mean_t, var_t = _run_op("bn_stats", stats, (x,), {})
        # update running stats in place (stop-gradient side channel)
        rm = running_mean._data.astype(jnp.float32)
        rv = running_var._data.astype(jnp.float32)
        running_mean._data = (momentum * rm
                              + (1 - momentum) * jax.lax.stop_gradient(mean_t._data)
                              ).astype(running_mean._data.dtype)
        running_var._data = (momentum * rv
                             + (1 - momentum) * jax.lax.stop_gradient(var_t._data)
                             ).astype(running_var._data.dtype)
        use_mean, use_var = mean_t, var_t
    else:
        use_mean, use_var = running_mean, running_var

    def f(a, m, v, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        a32 = a.astype(jnp.float32)
        out = (a32 - m.astype(jnp.float32).reshape(shape)) * jax.lax.rsqrt(
            v.astype(jnp.float32).reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (x, use_mean, use_var) + tuple(t for t in (weight, bias) if t is not None)
    return _run_op("batch_norm", f, args, {})


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        a32 = a.astype(jnp.float32)
        m = jnp.mean(a32, axis=axes, keepdims=True)
        v = jnp.var(a32, axis=axes, keepdims=True)
        out = (a32 - m) * jax.lax.rsqrt(v + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return _run_op("instance_norm", f, args, {})


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        rest = a.shape[2:]
        a32 = a.astype(jnp.float32).reshape((n, g, c // g) + rest)
        axes = tuple(range(2, a32.ndim))
        m = jnp.mean(a32, axis=axes, keepdims=True)
        v = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape)
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return _run_op("group_norm", f, args, {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pads)
        acc = sum(sq_p[:, i:i + c] for i in range(size))
        return a / (k + alpha * acc) ** beta
    return _run_op("lrn", f, (x,), {})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return _run_op("normalize", f, (x,), {})
