"""Pooling via lax.reduce_window (ref: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor, _run_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pad_spec(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(x), int(x)) for x in p]
    return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]


def _pool(x, kernel, stride, padding, n, reducer, init, name,
          ceil_mode=False, count_include_pad=True, data_format="NCHW"):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)
    chan_last = data_format in ("NHWC", "NLC", "NDHWC")

    def f(a):
        if chan_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = ([(0, 0)] + list(pad) + [(0, 0)]) if not isinstance(pad, str) else pad
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = ([(0, 0), (0, 0)] + list(pad)) if not isinstance(pad, str) else pad
        if reducer == "max":
            neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, neg, jax.lax.max, dims, strides, pads)
        # avg pool: sum then divide
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, pads)
        if count_include_pad or isinstance(pads, str):
            return (s / np.prod(kernel)).astype(a.dtype)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pads)
        return (s / counts).astype(a.dtype)

    return _run_op(name, f, (x,), {})


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NLC" if data_format == "NLC" else "NCW"
    if return_mask:
        if df == "NLC":
            raise NotImplementedError(
                "max_pool1d(return_mask=True) requires NCL data_format")
        return _max_pool_mask(x, kernel_size, stride, padding, 1, df,
                              "max_pool1d")
    return _pool(x, kernel_size, stride, padding, 1, "max", None, "max_pool1d",
                 data_format="NLC" if df == "NLC" else "NCHW")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise NotImplementedError(
                "max_pool2d(return_mask=True) requires NCHW data_format")
        return _max_pool_mask(x, kernel_size, stride, padding, 2, data_format,
                              "max_pool2d")
    return _pool(x, kernel_size, stride, padding, 2, "max", None, "max_pool2d",
                 ceil_mode=ceil_mode, data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":
            raise NotImplementedError(
                "max_pool3d(return_mask=True) requires NCDHW data_format")
        return _max_pool_mask(x, kernel_size, stride, padding, 3, data_format,
                              "max_pool3d")
    return _pool(x, kernel_size, stride, padding, 3, "max", None, "max_pool3d",
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, "avg_pool1d",
                 count_include_pad=not exclusive,
                 data_format="NLC" if data_format == "NLC" else "NCHW")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, "avg_pool2d",
                 count_include_pad=not exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, "avg_pool3d",
                 count_include_pad=not exclusive, data_format=data_format)




def _adaptive_edges(size, out):
    """torch/paddle adaptive pooling windows: start=floor(i*size/out),
    end=ceil((i+1)*size/out). Never empty, even when out > size."""
    starts = [(i * size) // out for i in range(out)]
    ends = [-(-((i + 1) * size) // out) for i in range(out)]
    return list(zip(starts, ends))

def adaptive_avg_pool1d(x, output_size, name=None):
    def f(a):
        l = a.shape[-1]
        out = int(output_size)
        return jnp.stack([a[..., s:e].mean(-1)
                          for s, e in _adaptive_edges(l, out)], axis=-1)
    return _run_op("adaptive_avg_pool1d", f, (x,), {})


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out = _tuple(output_size, 2)
    def f(a):
        h, w = (a.shape[2], a.shape[3]) if data_format == "NCHW" else (a.shape[1], a.shape[2])
        rows = []
        for hs, he in _adaptive_edges(h, out[0]):
            cols = []
            for ws, we in _adaptive_edges(w, out[1]):
                if data_format == "NCHW":
                    cols.append(a[:, :, hs:he, ws:we].mean((2, 3)))
                else:
                    cols.append(a[:, hs:he, ws:we, :].mean((1, 2)))
            rows.append(jnp.stack(cols, axis=-1))
        res = jnp.stack(rows, axis=-2)
        return res
    return _run_op("adaptive_avg_pool2d", f, (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    out = _tuple(output_size, 3)
    def f(a):
        d, h, w = a.shape[2:]
        vol = []
        for ds_, de in _adaptive_edges(d, out[0]):
            rows = []
            for hs, he in _adaptive_edges(h, out[1]):
                cols = []
                for ws, we in _adaptive_edges(w, out[2]):
                    cols.append(a[:, :, ds_:de, hs:he, ws:we].mean((2, 3, 4)))
                rows.append(jnp.stack(cols, -1))
            vol.append(jnp.stack(rows, -2))
        return jnp.stack(vol, -3)
    return _run_op("adaptive_avg_pool3d", f, (x,), {})


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _tuple(output_size, 2)
    def f(a):
        h, w = a.shape[2], a.shape[3]
        rows = []
        for hs, he in _adaptive_edges(h, out[0]):
            cols = [a[:, :, hs:he, ws:we].max((2, 3))
                    for ws, we in _adaptive_edges(w, out[1])]
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)
    return _run_op("adaptive_max_pool2d", f, (x,), {})


def global_avg_pool2d(x, data_format="NCHW", name=None):
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return _run_op("global_avg_pool2d", lambda a: a.mean(axes, keepdims=True), (x,), {})


# -- max pool with argmax mask + unpooling (ref: pooling.py max_pool2d
# return_mask=True + max_unpool{1,2,3}d; the reference's mask is the
# flattened spatial index of each window's max) ----------------------------

def _resolve_str_pad(pad, spatial, kernel, stride):
    """'SAME'/'VALID' -> numeric per-dim pads (XLA convention: out =
    ceil(in/stride), total pad split low-first)."""
    if pad == "VALID":
        return [(0, 0)] * len(spatial)
    out = []
    for i, sz in enumerate(spatial):
        o = -(-sz // stride[i])
        total = max((o - 1) * stride[i] + kernel[i] - sz, 0)
        out.append((total // 2, total - total // 2))
    return out


def _max_pool_mask(x, kernel, stride, padding, n, data_format, name):
    """Channel-first pooling returning (out, mask). Window patches are
    extracted with conv_general_dilated_patches; out = max over the patch
    axis (differentiable, grads route to the argmax) and mask = the
    reference's flattened spatial index of each window's max (int32,
    lowest index on ties)."""
    kernel_t = _tuple(kernel, n)
    stride_t = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)

    def f(a):
        spatial = a.shape[2:]
        c = a.shape[1]
        pads = list(pad) if not isinstance(pad, str) else \
            _resolve_str_pad(pad, spatial, kernel_t, stride_t)
        neg = jnp.asarray(-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                          else jnp.iinfo(a.dtype).min, a.dtype)
        ap = jnp.pad(a, [(0, 0), (0, 0)] + pads, constant_values=neg)
        patches = jax.lax.conv_general_dilated_patches(
            ap, kernel_t, stride_t, "VALID")
        # patches: [N, C*K, *out_spatial], features ordered (c, k0, k1, ...)
        out_sp = patches.shape[2:]
        k_sz = int(np.prod(kernel_t))
        patches = patches.reshape((a.shape[0], c, k_sz) + out_sp)
        out = patches.max(axis=2)
        local = jnp.argmax(patches, axis=2).astype(jnp.int32)  # [N,C,*out]
        # local kernel offset -> flat index in the UNPADDED input
        coords = []
        rem = local
        for d in range(n - 1, -1, -1):
            coords.insert(0, rem % kernel_t[d])
            rem = rem // kernel_t[d]
        flat = jnp.zeros_like(local)
        for d in range(n):
            grid_shape = [1] * local.ndim
            grid_shape[2 + d] = out_sp[d]
            start = jnp.arange(out_sp[d], dtype=jnp.int32).reshape(grid_shape)
            pos = start * stride_t[d] + coords[d] - pads[d][0]
            pos = jnp.clip(pos, 0, spatial[d] - 1)
            flat = flat * spatial[d] + pos
        return out, flat

    return _run_op(name, f, (x,), {})


def _max_unpool(x, indices, out_spatial, name):
    """Scatter pooled values back to their argmax positions (zeros
    elsewhere)."""
    def f(a, idx):
        nb, c = a.shape[:2]
        size = int(np.prod(out_spatial))
        flat = jnp.zeros((nb, c, size), a.dtype)
        ii = jnp.arange(nb)[:, None, None]
        jj = jnp.arange(c)[None, :, None]
        flat = flat.at[ii, jj, idx.reshape(nb, c, -1)].set(
            a.reshape(nb, c, -1))
        return flat.reshape((nb, c) + tuple(out_spatial))

    return _run_op(name, f, (x, indices), {})


def _unpool_out_size(in_sz, kernel, stride, padding, output_size, n):
    if output_size is not None:
        out = tuple(int(s) for s in output_size[-n:])
        return out
    if isinstance(padding, str):
        raise ValueError(
            "max_unpool with string padding needs an explicit output_size "
            "(the padded input size is ambiguous)")
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)
    return tuple((in_sz[i] - 1) * stride[i] - 2 * pad[i][0] + kernel[i]
                 for i in range(n))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    in_sz = tuple(int(s) for s in x.shape[2:])
    out = _unpool_out_size(in_sz, kernel_size, stride, padding, output_size, 1)
    return _max_unpool(x, indices, out, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    in_sz = tuple(int(s) for s in x.shape[2:])
    out = _unpool_out_size(in_sz, kernel_size, stride, padding, output_size, 2)
    return _max_unpool(x, indices, out, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    in_sz = tuple(int(s) for s in x.shape[2:])
    out = _unpool_out_size(in_sz, kernel_size, stride, padding, output_size, 3)
    return _max_unpool(x, indices, out, "max_unpool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1,
                    "lp_pool1d", chan_last=data_format == "NLC")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2,
                    "lp_pool2d", chan_last=data_format == "NHWC")


def _lp_pool(x, norm_type, kernel, stride, padding, n, name,
             chan_last=False):
    """(sum |x|^p)^(1/p) over windows (p=inf would be max_pool)."""
    p = float(norm_type)
    kernel_t = _tuple(kernel, n)
    stride_t = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)

    def f(a):
        if chan_last:  # pool spatial dims via the channel-first core
            a = jnp.moveaxis(a, -1, 1)
        dims = (1, 1) + kernel_t
        strides = (1, 1) + stride_t
        pads = ([(0, 0), (0, 0)] + list(pad)) if not isinstance(pad, str) else pad
        s = jax.lax.reduce_window(jnp.abs(a.astype(jnp.float32)) ** p, 0.0,
                                  jax.lax.add, dims, strides, pads)
        out = (s ** (1.0 / p)).astype(a.dtype)
        return jnp.moveaxis(out, 1, -1) if chan_last else out

    return _run_op(name, f, (x,), {})


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    """[N, C, L] -> [N, C, output_size] (ref: pooling.py)."""
    out = int(output_size) if not isinstance(output_size, (list, tuple)) \
        else int(output_size[0])

    if not return_mask:
        def f(a):
            L = a.shape[2]
            cols = [a[:, :, s:e].max(-1) for s, e in _adaptive_edges(L, out)]
            return jnp.stack(cols, axis=-1)
        return _run_op("adaptive_max_pool1d", f, (x,), {})

    # one op computes argmax once and derives the max from it
    def fboth(a):
        L = a.shape[2]
        cols = [s + a[:, :, s:e].argmax(-1)
                for s, e in _adaptive_edges(L, out)]
        mask = jnp.stack(cols, axis=-1).astype(jnp.int32)
        return jnp.take_along_axis(a, mask, axis=-1), mask

    res = _run_op("adaptive_max_pool1d_mask", fboth, (x,), {})
    return res[0], res[1]
