"""Pooling via lax.reduce_window (ref: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor, _run_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pad_spec(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(x), int(x)) for x in p]
    return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]


def _pool(x, kernel, stride, padding, n, reducer, init, name,
          ceil_mode=False, count_include_pad=True, data_format="NCHW"):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)
    chan_last = data_format in ("NHWC", "NLC", "NDHWC")

    def f(a):
        if chan_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = ([(0, 0)] + list(pad) + [(0, 0)]) if not isinstance(pad, str) else pad
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = ([(0, 0), (0, 0)] + list(pad)) if not isinstance(pad, str) else pad
        if reducer == "max":
            neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, neg, jax.lax.max, dims, strides, pads)
        # avg pool: sum then divide
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, pads)
        if count_include_pad or isinstance(pads, str):
            return (s / np.prod(kernel)).astype(a.dtype)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pads)
        return (s / counts).astype(a.dtype)

    return _run_op(name, f, (x,), {})


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NLC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, "max", None, "max_pool1d",
                 data_format="NLC" if df == "NLC" else "NCHW")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "max", None, "max_pool2d",
                 ceil_mode=ceil_mode, data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", None, "max_pool3d",
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, "avg_pool1d",
                 count_include_pad=not exclusive,
                 data_format="NLC" if data_format == "NLC" else "NCHW")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, "avg_pool2d",
                 count_include_pad=not exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, "avg_pool3d",
                 count_include_pad=not exclusive, data_format=data_format)




def _adaptive_edges(size, out):
    """torch/paddle adaptive pooling windows: start=floor(i*size/out),
    end=ceil((i+1)*size/out). Never empty, even when out > size."""
    starts = [(i * size) // out for i in range(out)]
    ends = [-(-((i + 1) * size) // out) for i in range(out)]
    return list(zip(starts, ends))

def adaptive_avg_pool1d(x, output_size, name=None):
    def f(a):
        l = a.shape[-1]
        out = int(output_size)
        return jnp.stack([a[..., s:e].mean(-1)
                          for s, e in _adaptive_edges(l, out)], axis=-1)
    return _run_op("adaptive_avg_pool1d", f, (x,), {})


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out = _tuple(output_size, 2)
    def f(a):
        h, w = (a.shape[2], a.shape[3]) if data_format == "NCHW" else (a.shape[1], a.shape[2])
        rows = []
        for hs, he in _adaptive_edges(h, out[0]):
            cols = []
            for ws, we in _adaptive_edges(w, out[1]):
                if data_format == "NCHW":
                    cols.append(a[:, :, hs:he, ws:we].mean((2, 3)))
                else:
                    cols.append(a[:, hs:he, ws:we, :].mean((1, 2)))
            rows.append(jnp.stack(cols, axis=-1))
        res = jnp.stack(rows, axis=-2)
        return res
    return _run_op("adaptive_avg_pool2d", f, (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    out = _tuple(output_size, 3)
    def f(a):
        d, h, w = a.shape[2:]
        vol = []
        for ds_, de in _adaptive_edges(d, out[0]):
            rows = []
            for hs, he in _adaptive_edges(h, out[1]):
                cols = []
                for ws, we in _adaptive_edges(w, out[2]):
                    cols.append(a[:, :, ds_:de, hs:he, ws:we].mean((2, 3, 4)))
                rows.append(jnp.stack(cols, -1))
            vol.append(jnp.stack(rows, -2))
        return jnp.stack(vol, -3)
    return _run_op("adaptive_avg_pool3d", f, (x,), {})


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _tuple(output_size, 2)
    def f(a):
        h, w = a.shape[2], a.shape[3]
        rows = []
        for hs, he in _adaptive_edges(h, out[0]):
            cols = [a[:, :, hs:he, ws:we].max((2, 3))
                    for ws, we in _adaptive_edges(w, out[1])]
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)
    return _run_op("adaptive_max_pool2d", f, (x,), {})


def global_avg_pool2d(x, data_format="NCHW", name=None):
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return _run_op("global_avg_pool2d", lambda a: a.mean(axes, keepdims=True), (x,), {})
