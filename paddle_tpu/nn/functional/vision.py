"""Vision functionals: grid_sample / affine_grid family
(ref: python/paddle/nn/functional/vision.py).

grid_sample gathers are XLA dynamic-gathers — batched and fused, no scalar
loops, so they stay TPU-friendly.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor.tensor import _run_op


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a 2D sampling grid from batched 2x3 affine matrices."""
    n, _, h, w = [int(s) for s in out_shape]
    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # (h*w, 3)
        out = jnp.einsum("nij,pj->npi", th.astype(jnp.float32), base)
        return out.reshape(n, h, w, 2).astype(th.dtype)
    return _run_op("affine_grid", f, (theta,), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample NCHW input at normalized grid locations (N, Hg, Wg, 2)."""
    def f(a, g):
        n, c, h, w = a.shape
        gf = g.astype(jnp.float32)
        gx, gy = gf[..., 0], gf[..., 1]
        if align_corners:
            fx = (gx + 1.0) * (w - 1) / 2.0
            fy = (gy + 1.0) * (h - 1) / 2.0
        else:
            fx = ((gx + 1.0) * w - 1.0) / 2.0
            fy = ((gy + 1.0) * h - 1.0) / 2.0

        def reflect(coord, size):
            if align_corners:
                span = size - 1
                coord = jnp.abs(coord)
                period = 2 * span if span > 0 else 1
                coord = coord % period
                return jnp.where(coord > span, period - coord, coord)
            span = size
            coord = jnp.abs(coord + 0.5)
            period = 2 * span
            coord = coord % period
            return jnp.clip(jnp.where(coord >= span, period - coord - 1e-6,
                                      coord) - 0.5, 0, size - 1)

        if padding_mode == "reflection":
            fx = reflect(fx, w)
            fy = reflect(fy, h)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            # (n, hg, wg) index grids -> (n, c, hg, wg) values
            vals = a[jnp.arange(n)[:, None, None, None],
                     jnp.arange(c)[None, :, None, None],
                     iyc[:, None], ixc[:, None]]
            if padding_mode == "zeros":
                inside = ((iy >= 0) & (iy <= h - 1) & (ix >= 0)
                          & (ix <= w - 1))[:, None]
                vals = jnp.where(inside, vals, 0.0)
            return vals

        if mode == "nearest":
            return gather(jnp.round(fy), jnp.round(fx)).astype(a.dtype)

        x0, y0 = jnp.floor(fx), jnp.floor(fy)
        x1, y1 = x0 + 1, y0 + 1
        wx1, wy1 = fx - x0, fy - y0
        wx0, wy0 = 1.0 - wx1, 1.0 - wy1
        out = (gather(y0, x0) * (wy0 * wx0)[:, None]
               + gather(y0, x1) * (wy0 * wx1)[:, None]
               + gather(y1, x0) * (wy1 * wx0)[:, None]
               + gather(y1, x1) * (wy1 * wx1)[:, None])
        return out.astype(a.dtype)
    return _run_op("grid_sample", f, (x, grid), {})


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        return a.reshape(n, c * r * r, h // r, w // r)
    return _run_op("pixel_unshuffle", f, (x,), {})


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = jnp.transpose(a, (0, 2, 1, 3, 4))
        return a.reshape(n, c, h, w)
    return _run_op("channel_shuffle", f, (x,), {})


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """Shift a fraction of channels one step along the segment (time) axis
    (ref: paddle.nn.functional.temporal_shift)."""
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]],
            axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return _run_op("temporal_shift", f, (x,), {})
