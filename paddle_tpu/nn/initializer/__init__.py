"""Weight initializers (ref: python/paddle/nn/initializer/).

Each initializer is a callable that fills a Parameter's data in place, drawing
randomness from the framework's global stateful RNG (so paddle.seed makes
initialization reproducible, TP layers re-seed per rank via the RNG tracker).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as random_mod


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError

    def _set(self, param, data):
        param._data = data.astype(param._data.dtype)


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        self._set(param, jnp.full(param._data.shape, self.value, jnp.float32))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        k = random_mod.next_key()
        self._set(param, jax.random.normal(k, param._data.shape, jnp.float32)
                  * self.std + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param, block=None):
        k = random_mod.next_key()
        data = jax.random.truncated_normal(k, self.a, self.b,
                                           param._data.shape, jnp.float32)
        self._set(param, data * self.std + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        k = random_mod.next_key()
        self._set(param, jax.random.uniform(k, param._data.shape, jnp.float32,
                                            self.low, self.high))


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: [out_c, in_c, *spatial] (reference layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._data.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = random_mod.next_key()
        self._set(param, jax.random.normal(k, param._data.shape, jnp.float32) * std)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._data.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = random_mod.next_key()
        self._set(param, jax.random.uniform(k, param._data.shape, jnp.float32,
                                            -limit, limit))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, param, block=None):
        fi, _ = _fans(param._data.shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        k = random_mod.next_key()
        self._set(param, jax.random.normal(k, param._data.shape, jnp.float32) * std)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, param, block=None):
        fi, _ = _fans(param._data.shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        k = random_mod.next_key()
        self._set(param, jax.random.uniform(k, param._data.shape, jnp.float32,
                                            -limit, limit))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, param, block=None):
        from ...tensor.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        else:
            v = jnp.asarray(np.asarray(v))
        self._set(param, v)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = param._data.shape
        data = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            data[idx] = 1.0
        self._set(param, jnp.asarray(data))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = param._data.shape
        rows = shape[0]
        cols = int(np.prod(shape)) // rows
        k = random_mod.next_key()
        a = jax.random.normal(k, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        q = q.T if rows < cols else q
        self._set(param, self.gain * q[:rows, :cols].reshape(shape))


# functional-style aliases matching paddle.nn.initializer names
constant_ = Constant
normal_ = Normal
uniform_ = Uniform
xavier_normal_ = XavierNormal
xavier_uniform_ = XavierUniform
kaiming_normal_ = KaimingNormal
kaiming_uniform_ = KaimingUniform


def set_global_initializer(weight_init, bias_init=None):
    # reference stores globals consulted by create_parameter; simple version:
    from ..layer import layers as _layers
    raise NotImplementedError("set_global_initializer: pass initializers via ParamAttr")


def calculate_gain(nonlinearity, param=None):
    """ref: nn.initializer.calculate_gain."""
    import math
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
             "conv3d_transpose": 1.0, "tanh": 5.0 / 3,
             "relu": math.sqrt(2.0), "selu": 3.0 / 4}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")
    return gains[nonlinearity]
