from . import layers
from .layers import Layer, Parameter, ParamAttr
