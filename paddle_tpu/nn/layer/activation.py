"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _simple(name, fn, **defaults):
    def forward(self, x):
        kwargs = {k: getattr(self, k) for k in defaults}
        return fn(x, **kwargs)

    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        for k, v in defaults.items():
            setattr(self, k, kwargs.get(k, v))

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", F.relu)
ReLU6 = _simple("ReLU6", F.relu6)
Sigmoid = _simple("Sigmoid", F.sigmoid)
LogSigmoid = _simple("LogSigmoid", F.log_sigmoid)
Tanh = _simple("Tanh", F.tanh)
Silu = _simple("Silu", F.silu)
Swish = Silu
Mish = _simple("Mish", F.mish)
Softsign = _simple("Softsign", F.softsign)
Tanhshrink = _simple("Tanhshrink", F.tanhshrink)
GELU = _simple("GELU", F.gelu, approximate=False)
Softmax = _simple("Softmax", F.softmax, axis=-1)
LogSoftmax = _simple("LogSoftmax", F.log_softmax, axis=-1)
Softplus = _simple("Softplus", F.softplus, beta=1, threshold=20)
LeakyReLU = _simple("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _simple("ELU", F.elu, alpha=1.0)
SELU = _simple("SELU", F.selu)
CELU = _simple("CELU", F.celu, alpha=1.0)
Hardshrink = _simple("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _simple("Softshrink", F.softshrink, threshold=0.5)
Hardsigmoid = _simple("Hardsigmoid", F.hardsigmoid)
Hardswish = _simple("Hardswish", F.hardswish)
Hardtanh = _simple("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
ThresholdedReLU = _simple("ThresholdedReLU", F.thresholded_relu, threshold=1.0)
Maxout = _simple("Maxout", F.maxout, groups=2, axis=1)
GLU = _simple("GLU", F.glu, axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel dimension of NCHW / CHW inputs
    (ref: python/paddle/nn/layer/activation.py Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects a 3D (CHW) or 4D (NCHW) input, got "
                f"{x.ndim}D")
        return F.softmax(x, axis=-3)
