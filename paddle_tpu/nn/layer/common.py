"""Common layers (ref: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import math

from .. import functional as F
from .. import initializer as I
from .layers import Layer, ParamAttr


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features] like the reference."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ...tensor.manipulation import reshape
        s = x.shape
        ax = self.axis % len(s)
        return reshape(x, s[:ax] + list(self.shape) + s[ax + 1:])


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.Uniform(-1.0 / math.sqrt(in1_features),
                                          1.0 / math.sqrt(in1_features)))
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear",
                         align_corners=True, data_format=data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class Threshold(Layer):
    """out = x if x > threshold else value (ref: nn.Threshold)."""

    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.threshold(x, self.threshold, self.value)
