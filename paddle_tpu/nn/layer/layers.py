"""Layer: the module system (ref: python/paddle/nn/layer/layers.py).

Same contract as the reference: parameter/buffer/sublayer registration via
``__setattr__``, ``state_dict``/``set_state_dict``, train/eval mode, forward
hooks, ``to``/dtype casting. Parameters are Tensors with
``stop_gradient=False``; everything composes with the eager autograd tape and
with ``paddle_tpu.jit`` functional tracing (parameters are swapped for tracers
during compilation — see jit/functional.py).
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ...framework import dtype as dtype_mod
from ...tensor.tensor import Tensor


class ParamAttr:
    """Parameter attribute bundle (ref: python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if callable(attr):  # bare initializer
            return ParamAttr(initializer=attr)
        raise TypeError(f"invalid ParamAttr: {attr!r}")


class Parameter(Tensor):
    """A trainable Tensor (ref: EagerParamBase)."""

    def __init__(self, data, trainable=True, name=None, learning_rate=1.0,
                 need_clip=True):
        if isinstance(data, Tensor):
            data = data._data
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": learning_rate}
        self.need_clip = need_clip
        self.is_distributed = False
        self.split_axis = None  # set by TP layers: which axis is mp-sharded

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_by_pure_fp16 = False
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            if params is not None:
                params.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor) or value is None:
                    buffers[name] = value
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        object.__setattr__(self, str(name), sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as init_mod
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype or "float32"
        nd = dtype_mod.convert_dtype(dtype)
        init = attr.initializer or default_initializer
        if init is None:
            init = init_mod.Constant(0.0) if is_bias else init_mod.XavierNormal()
        shape = [int(s) for s in shape]
        data = jnp.zeros(shape, nd)
        p = Parameter(data, trainable=attr.trainable, name=attr.name,
                      learning_rate=attr.learning_rate, need_clip=attr.need_clip)
        init(p)
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        nd = dtype_mod.convert_dtype(dtype or "float32")
        return Tensor(jnp.zeros((), nd), name=name)

    # -- traversal ---------------------------------------------------------
    def _named_members(self, get_members_fn, prefix="", include_sublayers=True):
        memo = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for layer_prefix, layer in layers:
            for k, v in get_members_fn(layer):
                if v is None or id(v) in memo:
                    continue
                memo.add(id(v))
                name = (layer_prefix + "." if layer_prefix else "") + k
                yield name, v

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        yield from self._named_members(lambda l: l._parameters.items(),
                                       prefix, include_sublayers)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        yield from self._named_members(lambda l: l._buffers.items(),
                                       prefix, include_sublayers)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        memo = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in memo:
                memo.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            p = (prefix + "." if prefix else "") + name
            yield from l.named_sublayers(prefix=p, include_self=True,
                                         layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        if destination is None:
            destination = collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                destination[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                destination[structured_name_prefix + name] = b
        if include_sublayers:
            for name, l in self._sub_layers.items():
                if l is not None:
                    l.state_dict(destination, include_sublayers,
                                 structured_name_prefix + name + ".")
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            data = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(data.shape) != tuple(target._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {data.shape} vs {target._data.shape}")
            target._data = data.astype(target._data.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- modes & casting ---------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        nd = dtype_mod.convert_dtype(dtype) if dtype is not None else None
        def _cast(t):
            if t is None:
                return
            if nd is not None and jnp.issubdtype(t._data.dtype, jnp.floating):
                t._data = t._data.astype(nd)
            if device is not None:
                t._data = t._to(device=device)
        for l in self.sublayers(include_self=True):
            for p in l._parameters.values():
                _cast(p)
            for b in l._buffers.values():
                _cast(b)
        if nd is not None:
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _LayerHookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _LayerHookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            sub = repr(l).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"({name}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}"
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


class _LayerHookHandle:
    _next_id = 0

    def __init__(self, hooks_dict):
        self._hooks = hooks_dict
        _LayerHookHandle._next_id += 1
        self.id = _LayerHookHandle._next_id

    def remove(self):
        self._hooks.pop(self.id, None)
