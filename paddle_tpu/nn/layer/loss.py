"""Loss layers (ref: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap = margin, p, epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full_, self.epsilon = log_input, full, epsilon
        self.reduction = reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full_,
                                  self.epsilon, self.reduction)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, reduction=self.reduction,
                                delta=self.delta)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label,
                                       margin=self.margin,
                                       reduction=self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, margin=self.margin,
                                      reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label,
                                              weight=self.weight,
                                              reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p, margin=self.margin,
                                   weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        d = self.distance_function or (
            lambda a, b: F.pairwise_distance(a, b))
        from ...tensor import math as tmath
        dp = d(input, positive)
        dn = d(input, negative)
        if self.swap:
            dpn = d(positive, negative)
            dn = tmath.minimum(dn, dpn)
        
        from ...tensor.creation import zeros_like
        loss = tmath.maximum(dp - dn + self.margin, zeros_like(dp))
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (ref: paddle.nn.AdaptiveLogSoftmaxWithLoss)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.div_value = div_value
        n_clusters = len(self.cutoffs) - 1
        head_size = self.cutoffs[0] + n_clusters
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias = (self.create_parameter([head_size], is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz])
            cls_w = self.create_parameter([hsz, osz])
            setattr(self, f"tail_proj_{i}", proj)
            setattr(self, f"tail_cls_{i}", cls_w)
            self.tail_weights.append((proj, cls_w))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            head_bias=self.head_bias)
