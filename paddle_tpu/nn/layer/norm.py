"""Norm layers (ref: python/paddle/nn/layer/norm.py). BatchNorm keeps running
stats as non-trainable buffers updated during training forward, like the
reference's _mean/_variance."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU SPMD, batch stats are computed over the global batch when the
    train step is jitted over a dp-sharded mesh (XLA inserts the cross-replica
    reduction for the mean/var all-reduce); eager single-host falls back to
    local stats, like the reference without a process group."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Root-mean-square norm (the reference ships it as a fused kernel for
    Llama; paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Spectral normalization of a weight tensor by power iteration
    (ref: paddle.nn.SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= int(s)
        self.register_buffer(
            "weight_u", Tensor(jax.random.normal(jax.random.PRNGKey(0), (h,),
                                                 jnp.float32)))
        self.register_buffer(
            "weight_v", Tensor(jax.random.normal(jax.random.PRNGKey(1), (w,),
                                                 jnp.float32)))

    def forward(self, weight):
        from ...tensor.tensor import _run_op
        dim, eps = self.dim, self.eps

        # Power iteration runs once, eagerly, outside the grad tape — like the
        # reference, gradients do not flow through u/v; they are buffers.
        wmat = jnp.moveaxis(
            (weight._data if isinstance(weight, Tensor) else weight),
            dim, 0).reshape(weight.shape[dim], -1).astype(jnp.float32)
        u, v = self.weight_u._data, self.weight_v._data
        for _ in range(self.power_iters):
            v = wmat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wmat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        self.weight_u._data = u
        self.weight_v._data = v

        def f(wt):
            wm = jnp.moveaxis(wt, dim, 0).reshape(wt.shape[dim], -1)
            sigma = u @ wm.astype(jnp.float32) @ v
            return (wt / sigma).astype(wt.dtype)
        return _run_op("spectral_norm", f, (weight,), {})
