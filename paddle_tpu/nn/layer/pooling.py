"""Pooling layers (ref: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool1d(x, self.k, self.s, self.p,
                            return_mask=self.return_mask)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool2d(x, self.k, self.s, self.p,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool3d(x, self.k, self.s, self.p,
                            return_mask=self.return_mask)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool1d(x, self.k, self.s, self.p, exclusive=self.exclusive)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive = exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.k, self.s, self.p,
                            exclusive=self.exclusive,
                            data_format=self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return F.avg_pool3d(x, self.k, self.s, self.p)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.k, self.s, self.p,
                              output_size=self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.k, self.s, self.p,
                              output_size=self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.k, self.s, self.p,
                              output_size=self.output_size)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type, self.k = norm_type, kernel_size
        self.s, self.p = stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.k, self.s, self.p,
                           data_format=self.data_format)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type, self.k = norm_type, kernel_size
        self.s, self.p = stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.k, self.s, self.p,
                           data_format=self.data_format)
