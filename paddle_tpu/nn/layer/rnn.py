"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py).

TPU-native: the time loop is a real recurrence, so it runs as ``lax.scan``
inside the taped op — XLA compiles one fused step and iterates it, instead of
the reference's cuDNN RNN descriptors. Layout: batch-first [B, T, ...] by
default with time_major option, matching the reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, _run_op
from .. import initializer as I
from .layers import Layer


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([gates * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([gates * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([gates * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([gates * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        self.activation = activation

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        if states is None:
            states = paddle.zeros([inputs.shape[0], self.hidden_size],
                                  dtype=inputs.dtype)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = _run_op("rnn_cell", f, (inputs, states, self.weight_ih,
                                    self.weight_hh, self.bias_ih, self.bias_hh), {})
        return h, h


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        if states is None:
            z = paddle.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
            states = (z, z)
        h_prev, c_prev = states
        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i, fg, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fg), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h, c = _run_op("lstm_cell", f, (inputs, h_prev, c_prev, self.weight_ih,
                                        self.weight_hh, self.bias_ih, self.bias_hh), {})
        return h, (h, c)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        if states is None:
            states = paddle.zeros([inputs.shape[0], self.hidden_size],
                                  dtype=inputs.dtype)
        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, -1)
            hr, hz, hc = jnp.split(gh, 3, -1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = _run_op("gru_cell", f, (inputs, states, self.weight_ih,
                                    self.weight_hh, self.bias_ih, self.bias_hh), {})
        return h, h


def _scan_rnn(mode, x, init, weights, time_major, reverse=False):
    """One direction of one layer, as lax.scan over time."""
    wi, wh, bi, bh = weights

    def lstm_step(carry, xt):
        h, c = carry
        gates = xt @ wi.T + bi + h @ wh.T + bh
        i, fg, g, o = jnp.split(gates, 4, axis=-1)
        i, fg, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fg), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = fg * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    def gru_step(h, xt):
        gi = xt @ wi.T + bi
        gh = h @ wh.T + bh
        ir, iz, ic = jnp.split(gi, 3, -1)
        hr, hz, hc = jnp.split(gh, 3, -1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        c = jnp.tanh(ic + r * hc)
        h = (1 - z) * c + z * h
        return h, h

    def rnn_step(h, xt):
        h = jnp.tanh(xt @ wi.T + bi + h @ wh.T + bh)
        return h, h

    step = {"LSTM": lstm_step, "GRU": gru_step, "RNN_TANH": rnn_step}[mode]
    xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, F]
    if reverse:
        xs = jnp.flip(xs, 0)
    final, ys = jax.lax.scan(step, init, xs)
    if reverse:
        ys = jnp.flip(ys, 0)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return final, ys


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gates = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer == 0 else hidden_size * self.bidirect
                sfx = f"l{layer}" + ("_reverse" if d else "")
                wi = self.create_parameter([gates * hidden_size, in_sz],
                                           weight_ih_attr, default_initializer=u)
                wh = self.create_parameter([gates * hidden_size, hidden_size],
                                           weight_hh_attr, default_initializer=u)
                bi = self.create_parameter([gates * hidden_size], bias_ih_attr,
                                           is_bias=True, default_initializer=u)
                bh = self.create_parameter([gates * hidden_size], bias_hh_attr,
                                           is_bias=True, default_initializer=u)
                self.add_parameter(f"weight_ih_{sfx}", wi)
                self.add_parameter(f"weight_hh_{sfx}", wh)
                self.add_parameter(f"bias_ih_{sfx}", bi)
                self.add_parameter(f"bias_hh_{sfx}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        b = inputs.shape[0] if not self.time_major else inputs.shape[1]
        n_state = self.num_layers * self.bidirect
        is_lstm = self.mode == "LSTM"
        if initial_states is None:
            z = paddle.zeros([n_state, b, self.hidden_size], dtype=inputs.dtype)
            initial_states = (z, paddle.zeros([n_state, b, self.hidden_size],
                                              dtype=inputs.dtype)) if is_lstm else z

        flat_ws = [w for tup in self._all_weights for w in tup]

        def f(x, *arrs):
            if is_lstm:
                h0, c0 = arrs[0], arrs[1]
                ws = arrs[2:]
            else:
                h0 = arrs[0]
                ws = arrs[1:]
            out = x
            hs, cs = [], []
            for layer in range(self.num_layers):
                outs_dir = []
                for d in range(self.bidirect):
                    i = layer * self.bidirect + d
                    weights = ws[4 * i: 4 * i + 4]
                    if is_lstm:
                        init = (h0[i], c0[i])
                    else:
                        init = h0[i]
                    final, ys = _scan_rnn(self.mode, out, init, weights,
                                          self.time_major, reverse=(d == 1))
                    outs_dir.append(ys)
                    if is_lstm:
                        hs.append(final[0]); cs.append(final[1])
                    else:
                        hs.append(final)
                out = outs_dir[0] if self.bidirect == 1 else \
                    jnp.concatenate(outs_dir, axis=-1)
            h_n = jnp.stack(hs)
            if is_lstm:
                return out, h_n, jnp.stack(cs)
            return out, h_n

        if is_lstm:
            args = (inputs, initial_states[0], initial_states[1]) + tuple(flat_ws)
            out, h, c = _run_op("lstm", f, args, {})
            return out, (h, c)
        args = (inputs, initial_states) + tuple(flat_ws)
        out, h = _run_op(self.mode.lower(), f, args, {})
        return out, h


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("RNN_TANH", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class RNN(Layer):
    """Wraps a cell into a recurrent layer (paddle.nn.RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # host loop over the cell (eager); acceptable for small T
        T_axis = 0 if self.time_major else 1
        T = inputs.shape[T_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = []
        states = initial_states
        from ...tensor import stack
        from ...tensor.tensor import Tensor as _T

        lens = None
        if sequence_length is not None:
            import jax.numpy as _jnp
            lens = (sequence_length._data if isinstance(sequence_length, _T)
                    else _jnp.asarray(sequence_length)).astype(_jnp.int32)

        def _mask_tree(new, old, keep):
            # keep: (B,) bool — take new where True else old (per-batch state)
            if isinstance(new, (tuple, list)):
                return type(new)(_mask_tree(n, o, keep)
                                 for n, o in zip(new, old if old is not None
                                                 else [None] * len(new)))
            import jax.numpy as _jnp
            k = keep.reshape((-1,) + (1,) * (new._data.ndim - 1))
            # old=None means the cell's zero initial state
            old_data = old._data if old is not None else _jnp.zeros_like(new._data)
            return _T(_jnp.where(k, new._data, old_data))

        for t in steps:
            xt = inputs[:, t] if T_axis == 1 else inputs[t]
            y, new_states = self.cell(xt, states)
            if lens is not None:
                import jax.numpy as _jnp
                valid = lens > t          # (B,)
                states = _mask_tree(new_states, states, valid)
                vy = valid.reshape((-1,) + (1,) * (y._data.ndim - 1))
                y = _T(_jnp.where(vy, y._data, _jnp.zeros_like(y._data)))
            else:
                states = new_states
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=T_axis), states


class BiRNN(Layer):
    """Bidirectional cell wrapper (ref: paddle.nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import concat
        if initial_states is None:
            fw_init = bw_init = None
        else:
            fw_init, bw_init = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_init, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_init, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
