"""Weight-only quantization ops (ref: python/paddle/nn/quant/
quantized_linear.py — weight_quantize / weight_dequantize /
weight_only_linear, the serving-side int8/int4 path behind the reference's
fused weight-only CUDA kernels).

TPU-native substitution: no custom kernel needed — XLA fuses the
int8->compute-dtype convert into the matmul's operand read (probed at
1.97x on a decode-shaped matvec; see models/llama.quantize_llama_int8
which uses the same layout), so `weight_only_linear` is a plain matmul
over the int8 weight plus a per-output-channel rescale. int4 packs two
nibbles per int8 byte (the reference's layout) and unpacks in-trace.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def absmax_intq(w, axis, bound=127.0):
    """Shared symmetric per-channel quantization core: returns
    (int8 codes, fp32 scale with keepdims) — the single implementation
    behind weight_quantize and models.llama.quantize_llama_int8."""
    f = jnp.asarray(w).astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=axis, keepdims=True)
                        / bound, 1e-8)
    q = jnp.clip(jnp.round(f / scale), -bound, bound).astype(jnp.int8)
    return q, scale


def weight_quantize(x, algo: str = "weight_only_int8", group_size: int = -1):
    """Quantize a [in, out] weight. Returns (quantized weight, scale[out]).

    algo: 'weight_only_int8' (symmetric per-output-channel int8) or
    'weight_only_int4' (two nibbles packed per byte along the IN axis,
    quantized weight shape [ceil(in/2), out]). group_size=-1 means
    per-channel over the whole in-dim (grouped scales are not supported —
    raise, don't silently mis-scale)."""
    if group_size != -1:
        raise NotImplementedError(
            "grouped weight quantization is not supported; use per-channel "
            "(group_size=-1)")
    if algo not in ("weight_only_int8", "weight_only_int4"):
        raise ValueError(f"unknown weight_quantize algo {algo!r}")
    w = _unwrap(x)
    if algo == "weight_only_int4" and w.shape[0] % 2:
        # the packed layout stores exactly in/2 bytes; an odd in-dim would
        # make the original size unrecoverable from the packed shape
        # (mirrors the reference kernels' alignment requirement)
        raise ValueError("weight_only_int4 requires an even in-dim, got "
                         f"{w.shape[0]}")
    bound = 127.0 if algo == "weight_only_int8" else 7.0
    q, scale = absmax_intq(w, axis=0, bound=bound)
    scale = jnp.squeeze(scale, 0)
    if algo == "weight_only_int4":
        lo = q[0::2]
        hi = q[1::2]
        # two's-complement nibbles: low in bits 0-3, high in bits 4-7
        q = ((hi.astype(jnp.int32) << 4) |
             (lo.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    return (Tensor._from_data(q),
            Tensor._from_data(scale.astype(_unwrap(x).dtype)))


def _unpack_int4(q, out_rows):
    qi = q.astype(jnp.int32)
    lo = (qi << 28) >> 28          # sign-extend low nibble
    hi = qi >> 4                   # arithmetic shift sign-extends high
    full = jnp.stack([lo, hi], axis=1).reshape(-1, q.shape[1])
    return full[:out_rows]


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype=None):
    """Inverse of weight_quantize (for checks/export)."""
    q = _unwrap(x)
    s = _unwrap(scale)
    if algo == "weight_only_int4":
        q = _unpack_int4(q, 2 * q.shape[0])
    w = q.astype(jnp.float32) * s.astype(jnp.float32)
    return Tensor._from_data(w.astype(out_dtype or s.dtype))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """y = x @ dequant(weight) + bias with the dequant fused into the
    matmul operand read (ref: weight_only_linear). weight: int8 [in, out]
    or packed int4 [in/2, out]; weight_scale: [out]."""
    if group_size != -1:
        raise NotImplementedError("grouped scales not supported")
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale")

    in_dim = _unwrap(x).shape[-1]

    def f(xv, wv, sv, *b):
        if weight_dtype == "int4":
            wf = _unpack_int4(wv, in_dim).astype(xv.dtype)
        else:
            wf = wv.astype(xv.dtype)
        y = (xv @ wf) * sv.astype(xv.dtype)
        if b:
            y = y + b[0]
        return y

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return _run_op("weight_only_linear", f, args, {})
