"""paddle.nn.utils (ref: python/paddle/nn/utils/): hook-based weight_norm /
spectral_norm reparameterizations, global-norm gradient clipping, and
parameter <-> flat-vector converters.

TPU-native notes: the reparameterizations recompute the effective weight
from their auxiliary parameters with TAPED tensor ops in a forward
pre-hook, so they compose with both the eager autograd tape and the
functional/jit path (functional_call swaps parameter arrays in place; the
hook then sees tracers and the recomputation is compiled into the step).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, _run_op
from ..layer.layers import Layer, Parameter

__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "clip_grad_norm_", "parameters_to_vector", "vector_to_parameters",
]


def _norm_except_dim(v, dim):
    """L2 norm over every axis except `dim` (keepdims, broadcastable
    against v). dim=None -> norm over everything (scalar shape)."""
    data = v._data if isinstance(v, Tensor) else v
    if dim is None:
        axes = tuple(range(data.ndim))
    else:
        axes = tuple(i for i in range(data.ndim) if i != dim)

    def f(a):
        sq = jnp.sum(jnp.square(a.astype(jnp.float32)), axis=axes,
                     keepdims=True)
        return jnp.sqrt(sq).astype(a.dtype)

    if isinstance(v, Tensor):
        return _run_op("norm_except_dim", f, (v,), {})
    return f(data)


def _compute_weight(g, v, dim):
    norm = _norm_except_dim(v, dim)
    return v * (g / norm)


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Reparameterize ``layer.<name>`` as direction × magnitude
    (w = g · v/‖v‖, ref: python/paddle/nn/utils/weight_norm_hook.py).

    Registers ``<name>_g`` (magnitude) and ``<name>_v`` (direction) as the
    trainable parameters; the effective weight is recomputed in a forward
    pre-hook. dim=None norms over the whole tensor."""
    if getattr(layer, "_weight_norm_hooks", None) and \
            name in layer._weight_norm_hooks:
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    g = Parameter(_norm_except_dim(w, dim)._data)
    v = Parameter(w._data)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def hook(lyr, inputs):
        object.__setattr__(
            lyr, name,
            _compute_weight(lyr._parameters[name + "_g"],
                            lyr._parameters[name + "_v"], dim))

    hook(layer, None)
    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        object.__setattr__(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (handle, dim)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    """Undo weight_norm: bakes the current effective weight back into a
    plain parameter and removes the hook."""
    hooks = getattr(layer, "_weight_norm_hooks", None)
    if not hooks or name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    handle, dim = hooks.pop(name)
    handle.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    w = _compute_weight(g, v, dim)
    for attr in (name + "_g", name + "_v"):
        if attr in layer.__dict__:
            object.__delattr__(layer, attr)
    layer.add_parameter(name, Parameter(w._data))
    return layer


def spectral_norm(layer: Layer, name: str = "weight",
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim: int = None):
    """Hook-based spectral normalization of ``layer.<name>``
    (ref: python/paddle/nn/utils/spectral_norm_hook.py): the effective
    weight is w_orig / σ(w_orig), with σ estimated by power iteration on
    buffers u/v (gradients do not flow through u/v, matching the
    reference)."""
    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    if dim is None:
        # the reference uses dim=1 for Linear (weight stored [in, out]),
        # 0 otherwise
        dim = 1 if type(layer).__name__ in ("Linear",) else 0
    shape = tuple(w._data.shape)
    h = shape[dim]
    wsz = 1
    for i, s in enumerate(shape):
        if i != dim:
            wsz *= int(s)
    orig = Parameter(w._data)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)
    layer.register_buffer(
        name + "_u",
        Tensor(jax.random.normal(jax.random.PRNGKey(0), (h,), jnp.float32)),
        persistable=True)
    layer.register_buffer(
        name + "_v",
        Tensor(jax.random.normal(jax.random.PRNGKey(1), (wsz,), jnp.float32)),
        persistable=True)

    def hook(lyr, inputs):
        wt = lyr._parameters[name + "_orig"]
        u = lyr._buffers[name + "_u"]._data
        v = lyr._buffers[name + "_v"]._data
        wmat = jnp.moveaxis(wt._data, dim, 0).reshape(h, -1) \
            .astype(jnp.float32)
        for _ in range(n_power_iterations):
            v = wmat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wmat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        lyr._buffers[name + "_u"]._data = u
        lyr._buffers[name + "_v"]._data = v

        def f(a):
            wm = jnp.moveaxis(a, dim, 0).reshape(h, -1).astype(jnp.float32)
            sigma = u @ wm @ v
            return (a / sigma).astype(a.dtype)

        object.__setattr__(lyr, name, _run_op("spectral_norm", f, (wt,), {}))

    hook(layer, None)
    layer.register_forward_pre_hook(hook)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Clip gradients IN PLACE so their global norm is at most max_norm
    (ref: python/paddle/nn/utils/clip_grad_norm_.py). Returns the
    pre-clip total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    parameters = list(parameters)  # may be a generator; iterated twice
    grads = [p.grad for p in parameters
             if p is not None and p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    max_norm = float(max_norm)
    norm_type = float(norm_type)
    gdatas = [g._data.astype(jnp.float32) for g in grads]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in gdatas]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in gdatas]))
        total = total ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of order {norm_type} for gradients is "
            "non-finite, so it cannot be clipped")
    clip_coef = max_norm / (total + 1e-6)
    clip_coef = jnp.minimum(clip_coef, 1.0)
    for p in parameters:
        if p is not None and p.grad is not None:
            p.grad._data = (p.grad._data.astype(jnp.float32)
                            * clip_coef).astype(p.grad._data.dtype)
    return Tensor(total)


def parameters_to_vector(parameters, name=None):
    """Flatten and concatenate parameters into one 1-D tensor
    (ref: python/paddle/nn/utils/transform_parameters.py)."""
    parts = [jnp.ravel(p._data) for p in parameters]
    return Tensor(jnp.concatenate(parts))


def vector_to_parameters(vec, parameters, name=None):
    """Slice a flat vector back into the given parameters, in place."""
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    offset = 0
    for p in parameters:
        n = int(p._data.size)
        chunk = data[offset:offset + n].reshape(p._data.shape) \
            .astype(p._data.dtype)
        p._data = chunk
        offset += n
    if offset != int(data.size):
        raise ValueError(
            f"vector has {int(data.size)} elements but parameters take "
            f"{offset}")
