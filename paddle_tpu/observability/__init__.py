"""Observability: step telemetry + comm/compute trace attribution.

Grown from the profiler stub in the spirit of XLA's xplane/TensorBoard
pipeline: ``StepMetrics`` collects wall step time, compile time, tokens/sec,
device memory and MFU with zero host syncs on the hot path; ``comm_span``
names every overlap site (TP ring hops, grad-sync buckets, 1F1B p2p,
shard_map islands) in the HLO metadata so device profiles attribute comm vs
compute; counters tally the static structure (hop counts, bucket bytes,
overlap on/off); exporters stream JSONL / TensorBoard scalars / rank-tagged
logs. Switched by ``PADDLE_TPU_TELEMETRY`` (+ ``PADDLE_TPU_TELEMETRY_DIR``
for the step log).
"""
from .exporters import (JsonlWriter, TensorBoardWriter, get_logger,  # noqa: F401
                        load_jsonl, log_event, process_rank)
from .metrics import (PEAK_FLOPS_TABLE, StepMetrics, active,  # noqa: F401
                      peak_flops_per_device, set_active)
from .trace import (ENV_TELEMETRY, ENV_TELEMETRY_DIR, comm_span,  # noqa: F401
                    counters, overlap_flags, record_counter, reset_counters,
                    set_counter, telemetry_dir, telemetry_enabled)
