"""Observability: tracing, streaming SLO metrics, and a flight recorder.

Grown from the profiler stub in the spirit of XLA's xplane/TensorBoard
pipeline, in three layers (PR 2 + PR 12):

1. **Trace attribution** — ``comm_span`` names every overlap site in the
   HLO metadata, counters tally static structure, and ``RequestTracer``
   gives every serving request a span tree (queue wait, prefill chunks,
   decode iterations, evictions) exported as JSONL / Chrome trace JSON
   (``write_chrome_trace``, shared with the profiler) for Perfetto.
2. **Streaming metrics** — ``StepMetrics`` collects wall step time,
   compile time, tokens/sec, device memory and MFU with zero host syncs
   on the hot path; ``LogHistogram`` keeps fixed-memory TTFT/TPOT/
   queue-wait/step-time distributions with live percentiles, rendered by
   ``render_prometheus`` for scraping.
3. **Failure flight recorder** — ``FlightRecorder`` rings the last N
   iteration/step records and dumps them to ``PADDLE_TPU_TELEMETRY_DIR``
   on exception, eviction storm, or MAD step-time spike.
4. **Fleet view** (PR 15) — ``MetricsRegistry`` is the single Prometheus
   exposition every surface registers into; ``FleetMonitor`` aggregates
   per-rank step times, per-``site=`` comm_span hop stats and all-device
   memory across ranks (one host-side allgather per interval), computes
   worst/median rank + straggler attribution + desync, and hooks
   non-finite-loss / grad-norm-spike / HBM-watermark anomalies into the
   shared flight-recorder ring.
5. **Performance attribution** (PR 17) — ``RooflineLedger`` itemizes
   step time into named kernel/component lines from the ``cost_estimate``
   FLOPs/bytes every pallas_call site declares, classifies each as
   compute- or memory-bound against the per-platform peak/HBM tables
   with an explicit unattributed remainder; ``merge_device_trace`` joins
   jax.profiler device events with host spans into one Perfetto view;
   ``regress`` ratchets bench rungs against ``PERF_BASELINE.json``.

Switched by ``PADDLE_TPU_TELEMETRY`` / ``PADDLE_TPU_TRACE_REQUESTS`` /
``PADDLE_TPU_FLIGHT_RECORDER`` / ``PADDLE_TPU_FLEET`` /
``PADDLE_TPU_LEDGER`` (+ ``PADDLE_TPU_TELEMETRY_DIR`` /
``PADDLE_TPU_LEDGER_DIR`` for file output).
"""
from .exporters import (JsonlWriter, TensorBoardWriter, get_logger,  # noqa: F401
                        load_jsonl, log_event, process_rank,
                        write_chrome_trace)
from .fleet import (FleetMonitor, device_memory_all,  # noqa: F401
                    fleet_enabled)
from .flight_recorder import (FlightRecorder, flight_recorder_enabled,  # noqa: F401
                              load_dump)
from .histogram import (LogHistogram, histogram_sample_lines,  # noqa: F401
                        render_prometheus)
from .ledger import (HBM_BW_TABLE, RooflineLedger,  # noqa: F401
                     flagship_component_specs, hbm_bw_per_device,
                     ledger_dir, ledger_enabled, load_device_trace_events,
                     merge_device_trace)
from .metrics import (PEAK_FLOPS_TABLE, StepMetrics, active,  # noqa: F401
                      peak_flops_info, peak_flops_per_device, set_active)
from .registry import MetricsRegistry  # noqa: F401
from .request_trace import RequestTracer  # noqa: F401
from .trace import (ENV_TELEMETRY, ENV_TELEMETRY_DIR, comm_span,  # noqa: F401
                    counters, overlap_flags, record_counter, reset_counters,
                    set_counter, telemetry_dir, telemetry_enabled)
