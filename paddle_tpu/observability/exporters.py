"""Telemetry exporters: JSONL step logs, TensorBoard scalars, rank logging.

All exporters share one duck type — ``write(record: dict)`` + ``close()`` —
so ``StepMetrics.attach`` composes them freely. Writes are buffered (flushed
every ``flush_every`` records and on close) so an attached exporter costs an
in-memory append on the hot path, not a syscall.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Optional


def _jsonable(obj):
    """json.dumps default= hook: numpy/jax scalars -> python numbers."""
    try:
        import numpy as np
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.generic):
            return obj.item()
    except Exception:
        pass
    if hasattr(obj, "item"):  # 0-d jax.Array (host fetch is the caller's call)
        try:
            return obj.item()
        except Exception:
            pass
    return str(obj)


class JsonlWriter:
    """Append-only JSONL step log (one record per line)."""

    def __init__(self, path: str, flush_every: int = 64):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self._f = open(path, "a")
        self._flush_every = max(1, int(flush_every))
        self._pending = 0

    def write(self, record: dict) -> None:
        self._f.write(json.dumps(record, default=_jsonable) + "\n")
        self._pending += 1
        if self._pending >= self._flush_every:
            self._f.flush()
            self._pending = 0

    def flush(self) -> None:
        self._f.flush()
        self._pending = 0

    def close(self) -> None:
        try:
            self._f.flush()
            self._f.close()
        except Exception:
            pass


def write_chrome_trace(path: str, events) -> str:
    """Write a Chrome trace-event JSON file (``{"traceEvents": [...]}``).

    The single writer behind both the profiler's host-span export and the
    serving engine's request traces, so every trace the repo emits opens
    in Perfetto / chrome://tracing the same way. ``events`` is an iterable
    of trace-event dicts (``ph`` "X"/"i"/"M" etc., µs timebase).
    """
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": list(events)}, f, default=_jsonable)
    return path


def load_jsonl(path: str):
    """Read a JSONL step log back into a list of dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class TensorBoardWriter:
    """TensorBoard scalar writer over whichever backend is installed
    (tensorboardX, torch.utils.tensorboard, or tf.summary). The import is
    OPTIONAL: construction raises ImportError with a clear message when no
    backend exists — gate on ``TensorBoardWriter.available()``."""

    def __init__(self, logdir: str):
        self._writer, self._mode = self._make(logdir)

    @staticmethod
    def _backend():
        try:
            from tensorboardX import SummaryWriter
            return SummaryWriter, "x"
        except ImportError:
            pass
        try:
            from torch.utils.tensorboard import SummaryWriter
            return SummaryWriter, "x"
        except ImportError:
            pass
        try:
            import tensorflow as tf
            return tf.summary.create_file_writer, "tf"
        except ImportError:
            pass
        return None, None

    @staticmethod
    def available() -> bool:
        return TensorBoardWriter._backend()[0] is not None

    def _make(self, logdir):
        ctor, mode = self._backend()
        if ctor is None:
            raise ImportError(
                "TensorBoardWriter needs tensorboardX, torch, or tensorflow; "
                "none is installed (JSONL export has no dependency)")
        return ctor(logdir), mode

    def write(self, record: dict) -> None:
        step = int(record.get("step", 0) or 0)
        tag_root = record.get("name", "train")
        for key, val in record.items():
            if key in ("name", "step") or val is None:
                continue
            try:
                val = float(val)
            except (TypeError, ValueError):
                continue
            if self._mode == "x":
                self._writer.add_scalar(f"{tag_root}/{key}", val, step)
            else:
                import tensorflow as tf
                with self._writer.as_default():
                    tf.summary.scalar(f"{tag_root}/{key}", val, step=step)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


def process_rank() -> int:
    """This process's rank: the launch env var before jax initializes,
    ``jax.process_index()`` after."""
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class _RankFilter(logging.Filter):
    def filter(self, record):
        record.rank = process_rank()
        return True


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    """Rank-tagged structured logger (shared by ``distributed/launch``).

    Plain messages format as ``[ts] [rank N] name LEVEL: msg``; use
    ``log_event(logger, event, **fields)`` for machine-parseable lines.
    """
    logger = logging.getLogger(name)
    if not any(isinstance(f, _RankFilter) for f in logger.filters):
        logger.addFilter(_RankFilter())
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s [rank %(rank)s] %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def log_event(logger: logging.Logger, event: str, level: int = logging.INFO,
              **fields) -> None:
    """Emit one structured (JSON) log line tagged with the process rank."""
    payload = {"event": event, "rank": process_rank()}
    payload.update(fields)
    logger.log(level, json.dumps(payload, default=_jsonable))
