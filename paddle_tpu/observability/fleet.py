"""Fleet-wide training observability: cross-rank aggregation + anomalies.

Everything before PR 15 itemized ONE rank of one host: StepMetrics
timed the local step, comm_span counters tallied the local program, and
``memory_stats()`` sampled device 0. ``FleetMonitor`` turns the
MULTICHIP dryrun's ad-hoc "worst-rank step time" pattern into a layer:

- **per-rank collection** (``on_step``) is a few host-side float appends
  — the step hot path keeps zero device syncs (the monitor never calls
  into jax on a step; callers hand it numbers they already have);
- **one small host-side allgather per reporting interval** shares each
  rank's step-time stats, per-``site=`` comm_span hop time/bytes deltas,
  and ALL local devices' ``memory_stats()``; the aggregate computes
  worst/median rank, per-site straggler attribution (which collective
  family is slowest on which rank), a desync detector (rank step-count
  divergence — e.g. one rank stuck recompiling), and the fleet HBM peak;
- **anomaly hooks** — non-finite loss, grad-norm MAD spike, HBM
  high-watermark, rank desync — append ``fleet_anomaly`` records to the
  shared PR-12 FlightRecorder ring and dump it with the offending rank
  and metric attached;
- every aggregated report lands in a ``fleet_health`` JSONL record;
  ``python -m paddle_tpu.observability.fleet --check <jsonl>`` validates
  schema + no-desync + the monitor-overhead bound (the multichip dryrun
  tail runs it on its own health log).

Knobs (all through the ``envs`` registry, PTA005): ``PADDLE_TPU_FLEET``
(wiring switch for ``jit.TrainStep``), ``PADDLE_TPU_FLEET_INTERVAL``
(steps between reports), ``PADDLE_TPU_FLEET_HBM_WATERMARK`` (fraction
of a device's byte limit that trips the high-watermark anomaly) and
``PADDLE_TPU_FLEET_DESYNC_STEPS`` (allowed rank step-count divergence).
"""
from __future__ import annotations

import argparse
import collections
import json
import math
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from .. import envs
from . import trace as _trace
from .exporters import _jsonable, process_rank
from .registry import MetricsRegistry

__all__ = ["FleetMonitor", "fleet_enabled", "device_memory_all",
           "check_file", "main", "REPORT_KIND"]

ENV_FLEET = "PADDLE_TPU_FLEET"
ENV_FLEET_INTERVAL = "PADDLE_TPU_FLEET_INTERVAL"
ENV_FLEET_HBM_WATERMARK = "PADDLE_TPU_FLEET_HBM_WATERMARK"
ENV_FLEET_DESYNC_STEPS = "PADDLE_TPU_FLEET_DESYNC_STEPS"

REPORT_KIND = "fleet_health"
# every fleet_health record must carry these (the --check schema)
REQUIRED_KEYS = ("kind", "world", "step", "step_time_ms", "sites",
                 "top_straggler_site", "hbm_peak_bytes", "desync",
                 "interval_wall_ms", "monitor_overhead_ms", "anomalies")
# the grad-norm MAD detector stays quiet below this many samples
# (median/MAD over warmup jitter flags nothing but noise)
MIN_GRAD_SAMPLES = 16
_MAD_SIGMA = 1.4826  # MAD -> sigma under normality


def fleet_enabled(explicit: Optional[bool] = None) -> bool:
    """Fleet-monitor switch: explicit argument wins, else the env knob."""
    if explicit is not None:
        return bool(explicit)
    return envs.get(ENV_FLEET)


def device_memory_all() -> List[Dict[str, Any]]:
    """Host-side PJRT ``memory_stats()`` of EVERY local device (no device
    sync — PJRT answers from the client). Backends that report nothing
    (host CPU) yield an empty list, which downstream renders as n/a."""
    out: List[Dict[str, Any]] = []
    try:
        devices = jax.local_devices()
    except Exception:
        devices = []
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append({"device": i,
                    "device_kind": getattr(dev, "device_kind", ""),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit")})
    return out


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class FleetMonitor:
    """Cross-rank step/collective/memory aggregation off the hot path.

    Per step the caller hands over numbers it already has on the host
    (``on_step(step_time_s=...)``; optionally ``loss=``/``grad_norm=``
    as HOST floats — the monitor never pulls a device value). Every
    ``interval`` steps the monitor builds its local rank report, runs
    ONE small host-side allgather, aggregates, updates its registry,
    appends a ``fleet_health`` JSONL record, and checks the anomaly
    hooks. All other steps cost two ``perf_counter`` reads and a list
    append.
    """

    def __init__(self, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 interval: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None, out_path: Optional[str] = None,
                 hbm_watermark: Optional[float] = None,
                 desync_steps: Optional[int] = None,
                 spike_mad: Optional[float] = None,
                 allgather: Optional[Callable[[Dict], List[Dict]]] = None):
        self.rank = rank if rank is not None else process_rank()
        self.world = world if world is not None else max(
            1, jax.process_count())
        self.interval = int(interval if interval is not None
                            else envs.get(ENV_FLEET_INTERVAL))
        self.hbm_watermark = float(
            hbm_watermark if hbm_watermark is not None
            else envs.get(ENV_FLEET_HBM_WATERMARK))
        self.desync_steps = int(desync_steps if desync_steps is not None
                                else envs.get(ENV_FLEET_DESYNC_STEPS))
        self.spike_mad = float(spike_mad if spike_mad is not None
                               else envs.get("PADDLE_TPU_SPIKE_MAD"))
        self.allgather = allgather if allgather is not None \
            else self._default_allgather
        self.recorder = recorder  # shared PR-12 FlightRecorder ring
        self.registry = registry if registry is not None \
            else MetricsRegistry(prefix="paddle_tpu_fleet")
        self.out_path = out_path
        self.steps_done = 0
        self.reports: List[Dict] = []
        self.anomalies: List[Dict] = []
        self._step_times: List[float] = []     # since last report
        self._grad_norms: collections.deque = collections.deque(maxlen=128)
        self._site_base: Dict[str, float] = {}
        self._overhead_s = 0.0
        self._overhead_reported = 0.0
        self._interval_t0 = time.perf_counter()
        self._anoms_reported = 0
        self._register_metrics()

    # -- registry wiring -----------------------------------------------------

    def _register_metrics(self) -> None:
        r = self.registry
        self._m_reports = r.counter(
            "reports_total", help="fleet health reports emitted")
        self._m_hist = r.summary(
            "local_step_time_seconds", lo=1e-5, hi=1e4,
            help="this rank's step wall-time distribution")
        self._m_worst = r.gauge(
            "step_time_ms_worst",
            help="worst-rank mean step time over the last interval (ms)")
        self._m_median = r.gauge(
            "step_time_ms_median",
            help="median-rank mean step time over the last interval (ms)")
        self._m_worst_rank = r.gauge(
            "worst_rank", help="rank with the slowest mean step time")
        self._m_desync = r.gauge(
            "desync_max_ahead",
            help="max rank step-count divergence at the last report")
        self._m_hbm = r.gauge(
            "hbm_peak_bytes",
            help="fleet-wide peak HBM bytes across all reporting devices")
        self._m_site_ms = r.family(
            "site_hop_ms", "gauge", labelnames=("site",),
            help="worst-rank host ms inside each comm_span site over the "
                 "last interval")
        self._m_site_bytes = r.family(
            "site_hop_bytes", "gauge", labelnames=("site",),
            help="total bytes attributed to each comm_span site over the "
                 "last interval")

    # -- per-step collection (hot path: host floats only) --------------------

    def on_step(self, step_time_s: Optional[float] = None,
                loss: Optional[float] = None,
                grad_norm: Optional[float] = None) -> Optional[Dict]:
        """Record one local step; returns the aggregated fleet report on
        interval boundaries, else None. All arguments must already be
        host values — passing a device array here would add the very
        sync this layer is designed to avoid."""
        t0 = time.perf_counter()
        self.steps_done += 1
        if step_time_s is not None:
            v = float(step_time_s)
            self._step_times.append(v)
            self._m_hist.observe(v)
        if loss is not None:
            self.observe_loss(loss)
        if grad_norm is not None:
            self.observe_grad_norm(grad_norm)
        report = None
        if self.interval > 0 and self.steps_done % self.interval == 0:
            report = self._report(t0)
        self._overhead_s += time.perf_counter() - t0
        return report

    # -- anomaly hooks -------------------------------------------------------

    def _anomaly(self, kind: str, **fields) -> Dict:
        rec = {"kind": kind, "rank": self.rank, "step": self.steps_done}
        rec.update(fields)
        self.anomalies.append(rec)
        if self.recorder is not None:
            # shared PR-12 ring: the fleet event rides next to the step
            # records so the dump shows what the rank was doing around it
            self.recorder.record({"iteration": self.steps_done,
                                  "event": "fleet_anomaly", **rec})
            self.recorder.anomalies.append(rec)
            self.recorder.dump(kind)
        return rec

    def observe_loss(self, value: float) -> Optional[Dict]:
        """Non-finite-loss hook over a HOST float the caller already has
        (a logging loop's ``float(loss)``); never syncs to fetch one."""
        v = float(value)
        if not math.isfinite(v):
            return self._anomaly("nonfinite_loss", metric="loss", value=v)
        return None

    def observe_grad_norm(self, value: float) -> Optional[Dict]:
        """Grad-norm MAD spike hook: a norm beyond ``spike_mad`` robust
        sigmas from the rolling-window median (the loss-scale-blowup /
        bad-batch signature), plus the non-finite screen."""
        v = float(value)
        if not math.isfinite(v):
            return self._anomaly("nonfinite_loss", metric="grad_norm",
                                 value=v)
        prior = list(self._grad_norms)
        self._grad_norms.append(v)
        if len(prior) < MIN_GRAD_SAMPLES:
            return None
        med = _median(prior)
        sigma = _MAD_SIGMA * _median([abs(x - med) for x in prior])
        spike = (abs(v - med) > self.spike_mad * sigma if sigma > 0
                 else v > med * self.spike_mad)
        if spike:
            return self._anomaly("grad_norm_spike", metric="grad_norm",
                                 value=v, median=med,
                                 mad_sigma=sigma / _MAD_SIGMA,
                                 threshold_mads=self.spike_mad)
        return None

    # -- interval reporting --------------------------------------------------

    def _site_deltas(self) -> Dict[str, Dict[str, float]]:
        """Per-site comm_span counter deltas since the last report.
        Counter resets (``reset_counters()``) are detected per key — a
        value below its base restarts the delta from the raw value."""
        cur = {k: v for k, v in _trace.counters().items()
               if k.startswith("site.")}
        out: Dict[str, Dict[str, float]] = {}
        for key, val in cur.items():
            site, _, field = key[len("site."):].rpartition(".")
            if field not in ("calls", "bytes", "ms") or not site:
                continue
            base = self._site_base.get(key, 0.0)
            delta = val - base if val >= base else val
            if delta:
                out.setdefault(site, {})[field] = delta
        self._site_base = cur
        return out

    def local_report(self) -> Dict[str, Any]:
        """This rank's payload for the interval allgather: step-time
        stats since the last report, per-site comm deltas, and every
        local device's memory stats."""
        times = self._step_times
        stats: Dict[str, Any] = {"count": len(times)}
        if times:
            stats["mean"] = sum(times) / len(times) * 1e3
            stats["max"] = max(times) * 1e3
        return {"rank": self.rank, "steps_done": self.steps_done,
                "step_time_ms": stats,
                "sites": self._site_deltas(),
                "devices": device_memory_all()}

    @staticmethod
    def aggregate(rank_reports: List[Dict]) -> Dict[str, Any]:
        """Fold per-rank payloads into one fleet view: worst/median rank
        step time, per-site straggler attribution (worst rank + cross-
        rank spread per comm_span site), fleet HBM peak, and the rank
        step-count desync. Pure function of the gathered payloads."""
        reports = [r for r in rank_reports if r]
        per_rank = [(r["rank"], r["step_time_ms"]["mean"])
                    for r in reports
                    if r.get("step_time_ms", {}).get("mean") is not None]
        step_time: Dict[str, Any] = {"worst": None, "median": None,
                                     "worst_rank": None}
        if per_rank:
            worst_rank, worst = max(per_rank, key=lambda rv: rv[1])
            step_time = {"worst": worst,
                         "median": _median([v for _, v in per_rank]),
                         "worst_rank": worst_rank}
        sites: Dict[str, Dict[str, Any]] = {}
        names = sorted({s for r in reports for s in (r.get("sites") or {})})
        for site in names:
            entries = [(r["rank"], r["sites"][site]) for r in reports
                       if site in (r.get("sites") or {})]
            ms = [(rk, d.get("ms", 0.0)) for rk, d in entries]
            worst_rank, worst_ms = max(ms, key=lambda rv: rv[1])
            median_ms = _median([v for _, v in ms])
            sites[site] = {
                "worst_rank": worst_rank,
                "worst_ms": worst_ms,
                "median_ms": median_ms,
                "spread_ms": worst_ms - median_ms,
                "bytes": sum(d.get("bytes", 0.0) for _, d in entries),
                "calls": sum(d.get("calls", 0.0) for _, d in entries),
            }
        top = None
        if sites:
            spreads = {s: v["spread_ms"] for s, v in sites.items()}
            if any(v > 0 for v in spreads.values()):
                top = max(spreads, key=spreads.get)
            else:  # single rank (or perfectly even): attribute by cost
                top = max(sites, key=lambda s: sites[s]["worst_ms"])
        devices = [{**d, "rank": r["rank"]}
                   for r in reports for d in (r.get("devices") or [])]
        peaks = [d["peak_bytes_in_use"] for d in devices
                 if d.get("peak_bytes_in_use") is not None]
        steps = {str(r["rank"]): r["steps_done"] for r in reports}
        max_ahead = (max(steps.values()) - min(steps.values())
                     if steps else 0)
        return {
            "kind": REPORT_KIND,
            "world": len(reports),
            "step": max(steps.values()) if steps else 0,
            "step_time_ms": step_time,
            "sites": sites,
            "top_straggler_site": top,
            "devices": devices,
            "hbm_peak_bytes": max(peaks) if peaks else None,
            "desync": {"max_ahead": max_ahead, "steps": steps},
        }

    def _default_allgather(self, payload: Dict) -> List[Dict]:
        """One host-side allgather of the (small) JSON payload. Single
        process returns the local payload; multi-process ships it as a
        padded uint8 buffer through ``multihost_utils`` — two tiny
        gathers per interval, nothing on the step itself."""
        if jax.process_count() <= 1:
            return [payload]
        import numpy as np
        from jax.experimental import multihost_utils
        raw = json.dumps(payload, default=_jsonable).encode()
        sizes = multihost_utils.process_allgather(
            np.asarray(len(raw), np.int32))
        cap = int(sizes.max())
        buf = np.zeros(cap, np.uint8)
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
        gathered = multihost_utils.process_allgather(buf)
        return [json.loads(bytes(gathered[i][:int(sizes[i])]).decode())
                for i in range(len(sizes))]

    def _report(self, t0: float) -> Dict[str, Any]:
        gathered = self.allgather(self.local_report())
        agg = self.aggregate(gathered)
        # desync + HBM watermark hooks run on the AGGREGATED view, so a
        # healthy rank still raises the alarm for a stuck/overcommitted one
        if agg["desync"]["max_ahead"] > self.desync_steps:
            self._anomaly("rank_desync",
                          max_ahead=agg["desync"]["max_ahead"],
                          steps=agg["desync"]["steps"],
                          allowed=self.desync_steps)
        for d in agg["devices"]:
            limit, peak = d.get("bytes_limit"), d.get("peak_bytes_in_use")
            if limit and peak and peak / limit > self.hbm_watermark:
                self._anomaly("hbm_high_watermark", metric="hbm_peak",
                              rank=d["rank"], device=d.get("device"),
                              fraction=peak / limit,
                              watermark=self.hbm_watermark,
                              peak_bytes=peak, limit_bytes=limit)
        now = time.perf_counter()
        total_overhead = self._overhead_s + (now - t0)
        agg["monitor_overhead_ms"] = \
            (total_overhead - self._overhead_reported) * 1e3
        self._overhead_reported = total_overhead
        agg["interval_wall_ms"] = (now - self._interval_t0) * 1e3
        self._interval_t0 = now
        agg["anomalies"] = self.anomalies[self._anoms_reported:]
        self._anoms_reported = len(self.anomalies)
        self._update_registry(agg)
        self.reports.append(agg)
        self._step_times = []
        if self.out_path:
            with open(self.out_path, "a") as fh:
                json.dump(agg, fh, default=_jsonable)
                fh.write("\n")
        return agg

    def _update_registry(self, agg: Dict[str, Any]) -> None:
        self._m_reports.inc()
        st = agg["step_time_ms"]
        if st["worst"] is not None:
            self._m_worst.set(st["worst"])
            self._m_median.set(st["median"])
            self._m_worst_rank.set(st["worst_rank"])
        self._m_desync.set(agg["desync"]["max_ahead"])
        if agg["hbm_peak_bytes"] is not None:
            self._m_hbm.set(agg["hbm_peak_bytes"])
        for site, v in agg["sites"].items():
            self._m_site_ms.labels(site=site).set(v["worst_ms"])
            self._m_site_bytes.labels(site=site).set(v["bytes"])

    # -- human view ----------------------------------------------------------

    def health_lines(self, tag: Optional[str] = None) -> List[str]:
        """The per-rung fleet health report the dryrun prints."""
        prefix = f"fleet[{tag}]" if tag else "fleet"
        if not self.reports:
            return [f"{prefix}: no reports yet"]
        r = self.reports[-1]
        st = r["step_time_ms"]
        if st["worst"] is not None:
            l1 = (f"{prefix}: world={r['world']} step={r['step']} "
                  f"worst_rank_step={st['worst']:.2f}ms@rank"
                  f"{st['worst_rank']} median={st['median']:.2f}ms")
        else:
            l1 = (f"{prefix}: world={r['world']} step={r['step']} "
                  f"step_time=n/a (no timed steps this interval)")
        top = r["top_straggler_site"]
        if top is not None:
            s = r["sites"][top]
            l2 = (f"{prefix}: straggler site={top} "
                  f"worst={s['worst_ms']:.2f}ms@rank{s['worst_rank']} "
                  f"median={s['median_ms']:.2f}ms bytes={s['bytes']:.0f}")
        else:
            l2 = (f"{prefix}: straggler site=n/a "
                  f"(no labeled comm_span traffic this interval)")
        if r["hbm_peak_bytes"] is not None:
            hbm = (f"hbm_peak={r['hbm_peak_bytes'] / 2 ** 20:.1f}MiB "
                   f"over {len(r['devices'])} device(s)")
        else:
            hbm = "hbm=n/a (backend reports no memory_stats)"
        l3 = (f"{prefix}: {hbm} "
              f"desync_max_ahead={r['desync']['max_ahead']} "
              f"anomalies={len(r['anomalies'])} "
              f"overhead={r['monitor_overhead_ms']:.2f}ms"
              f"/{r['interval_wall_ms']:.0f}ms")
        return [l1, l2, l3]


# -- CLI: validate a dryrun's fleet-health JSONL -----------------------------

def check_file(path: str, max_overhead_pct: float = 2.0,
               max_desync: Optional[int] = None):
    """Validate a fleet-health JSONL: every line parses as a
    ``fleet_health`` record with the full schema, no report exceeds the
    allowed rank desync, and the attributed monitor overhead stays under
    ``max_overhead_pct`` of each interval's wall time. Returns
    ``(n_records, problems)``."""
    if max_desync is None:
        max_desync = envs.get(ENV_FLEET_DESYNC_STEPS)
    n = 0
    problems: List[str] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                problems.append(f"line {lineno}: not valid JSON ({e})")
                continue
            if rec.get("kind") != REPORT_KIND:
                problems.append(f"line {lineno}: kind={rec.get('kind')!r}, "
                                f"expected {REPORT_KIND!r}")
                continue
            n += 1
            missing = [k for k in REQUIRED_KEYS if k not in rec]
            if missing:
                problems.append(f"line {lineno}: missing keys {missing}")
                continue
            st = rec["step_time_ms"]
            if not isinstance(st, dict) or not {"worst", "median",
                                                "worst_rank"} <= set(st):
                problems.append(f"line {lineno}: malformed step_time_ms "
                                f"{st!r}")
            desync = rec["desync"] or {}
            ahead = desync.get("max_ahead", 0)
            if ahead > max_desync:
                problems.append(
                    f"line {lineno}: rank desync {ahead} steps "
                    f"(allowed {max_desync}); steps={desync.get('steps')}")
            wall, over = rec["interval_wall_ms"], rec["monitor_overhead_ms"]
            if (isinstance(wall, (int, float)) and wall > 0
                    and isinstance(over, (int, float))):
                pct = over / wall * 100.0
                if pct > max_overhead_pct:
                    problems.append(
                        f"line {lineno}: monitor overhead {pct:.2f}% of "
                        f"interval wall (bound {max_overhead_pct}%)")
    if n == 0:
        problems.append(f"{path}: no {REPORT_KIND} records found")
    return n, problems


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.fleet",
        description="Validate a fleet-health JSONL (schema + no-desync + "
                    "monitor-overhead bound).")
    parser.add_argument("--check", metavar="JSONL", required=True,
                        help="path of a FleetMonitor out_path JSONL")
    parser.add_argument("--max-overhead-pct", type=float, default=2.0,
                        help="allowed monitor overhead as %% of interval "
                             "wall time (default 2)")
    parser.add_argument("--max-desync", type=int, default=None,
                        help="allowed rank step-count divergence "
                             "(default: PADDLE_TPU_FLEET_DESYNC_STEPS)")
    args = parser.parse_args(argv)
    n, problems = check_file(args.check, args.max_overhead_pct,
                             args.max_desync)
    if problems:
        for msg in problems:
            print(f"fleet_check: {msg}", file=sys.stderr)
        print(f"fleet_check: {os.path.basename(args.check)} reports={n} "
              f"FAILED ({len(problems)} problem(s))")
        return 1
    print(f"fleet_check: {os.path.basename(args.check)} reports={n} "
          f"schema_ok=True desync_ok=True overhead_ok=True OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
