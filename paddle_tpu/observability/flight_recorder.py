"""Failure flight recorder: a bounded ring of recent iteration records.

Post-mortems should not depend on having had tracing enabled. The
recorder keeps the last N iteration/step records (queue depth, batch
occupancy, pool utilization, per-phase ms, compile events — whatever
dict the caller hands it) in a fixed-size ring, costing one deque append
per step, and dumps the ring as JSON to ``PADDLE_TPU_TELEMETRY_DIR``
when something goes wrong:

- **exception** — the engine/TrainStep driving loop re-raises after
  ``dump("exception")``, so the crash report carries the last N steps;
- **eviction storm** — eviction rate over a sliding window crosses
  ``STORM_RATE`` (a thrashing pool: requests recompute more than they
  decode);
- **step-time spike** — a step lands ``spike_mad`` robust sigmas from
  the window median (MAD × 1.4826 ≈ σ under normality), the classic
  sign of a recompile, host stall, or preemption hiccup.

Each trigger dumps at most once per recorder (a storm would otherwise
write a file per iteration). Everything here is host-side Python over
values already on the host — no device syncs.
"""
from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional

from .. import envs
from .exporters import _jsonable
from .trace import telemetry_dir

__all__ = ["FlightRecorder", "flight_recorder_enabled", "STORM_WINDOW",
           "STORM_RATE", "MIN_SPIKE_SAMPLES"]

ENV_FLIGHT_RECORDER = "PADDLE_TPU_FLIGHT_RECORDER"
ENV_FLIGHT_RECORDER_SIZE = "PADDLE_TPU_FLIGHT_RECORDER_SIZE"
ENV_SPIKE_MAD = "PADDLE_TPU_SPIKE_MAD"

# Eviction-storm policy: more than STORM_RATE evictions per iteration
# averaged over the last STORM_WINDOW iterations is thrashing.
STORM_WINDOW = 32
STORM_RATE = 0.5
# The MAD detector stays quiet until it has seen this many step times
# (median/MAD over fewer samples flags ordinary warmup jitter).
MIN_SPIKE_SAMPLES = 16
_MAD_SIGMA = 1.4826  # MAD -> sigma under normality
# Median/MAD are refit every this many steps, not every step: the window
# statistics drift slowly, and the two sorts per fit would otherwise be
# the recorder's entire per-iteration cost. A suspected spike always
# refits fresh before firing, so stale stats never cause a false dump.
_SPIKE_REFIT_EVERY = 16


def flight_recorder_enabled(explicit: Optional[bool] = None) -> bool:
    """Recorder switch: explicit argument wins, else the env knob."""
    if explicit is not None:
        return bool(explicit)
    return envs.get(ENV_FLIGHT_RECORDER)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class FlightRecorder:
    """Fixed-size ring of iteration records + anomaly triggers.

    >>> rec = FlightRecorder(source="engine")
    >>> rec.record({"iteration": i, "queue_depth": q, ...})
    >>> rec.note_eviction(iteration=i)           # on each preemption
    >>> rec.check_step_time(step_time_s)          # MAD spike detector
    >>> rec.dump("exception")                     # on crash, then re-raise
    """

    def __init__(self, source: str = "engine", size: Optional[int] = None,
                 spike_mad: Optional[float] = None,
                 out_dir: Optional[str] = None):
        self.source = source
        self.size = int(size if size is not None
                        else envs.get(ENV_FLIGHT_RECORDER_SIZE))
        self.spike_mad = float(spike_mad if spike_mad is not None
                               else envs.get(ENV_SPIKE_MAD))
        self.out_dir = out_dir
        self.ring: collections.deque = collections.deque(maxlen=self.size)
        self._step_times: collections.deque = collections.deque(
            maxlen=self.size)
        self._evictions: collections.deque = collections.deque()
        self._spike_med: Optional[float] = None
        self._spike_sigma = 0.0
        self._since_refit = 0
        self._iteration = 0
        self.dumped: List[str] = []          # paths written this run
        self._fired: set = set()             # one dump per trigger kind
        self.anomalies: List[Dict[str, Any]] = []

    # -- recording ------------------------------------------------------------

    def record(self, rec: Dict[str, Any]) -> None:
        """Append one iteration/step record to the ring (O(1), no copy of
        older entries; the deque drops the oldest at capacity)."""
        self._iteration = int(rec.get("iteration", self._iteration + 1))
        self.ring.append(rec)

    def record_compile(self, kind: str, compile_s: float) -> None:
        self.ring.append({"iteration": self._iteration, "event": "compile",
                          "kind": kind, "compile_s": compile_s})

    # -- triggers -------------------------------------------------------------

    def note_eviction(self, iteration: int) -> Optional[str]:
        """Track one preemption; dumps when the sliding-window eviction
        rate crosses the storm threshold. Returns the dump path if fired."""
        self._evictions.append(iteration)
        floor = iteration - STORM_WINDOW
        while self._evictions and self._evictions[0] <= floor:
            self._evictions.popleft()
        rate = len(self._evictions) / STORM_WINDOW
        if rate > STORM_RATE:
            self.anomalies.append({"kind": "eviction_storm",
                                   "iteration": iteration,
                                   "rate_per_iter": rate})
            return self.dump("eviction_storm")
        return None

    def _refit_spike(self) -> None:
        """Recompute the cached window median/MAD (excluding the sample
        just appended, so a spike never masks itself)."""
        xs = list(self._step_times)
        xs.pop()
        med = _median(xs)
        mad = _median([abs(x - med) for x in xs])
        self._spike_med = med
        self._spike_sigma = _MAD_SIGMA * mad
        self._since_refit = 0

    def _is_spike(self, v: float) -> bool:
        med, sigma = self._spike_med, self._spike_sigma
        if sigma <= 0:
            # degenerate window (identical times, e.g. mocked clocks):
            # fall back to a pure multiple-of-median test
            return v > med * self.spike_mad
        return abs(v - med) > self.spike_mad * sigma

    def check_step_time(self, step_time_s: float) -> Optional[str]:
        """MAD-based spike detector over the recent step-time window.
        Returns the dump path when a spike fires, else None."""
        prior = len(self._step_times)
        self._step_times.append(float(step_time_s))
        if prior < MIN_SPIKE_SAMPLES:
            return None
        self._since_refit += 1
        if self._spike_med is None or self._since_refit >= _SPIKE_REFIT_EVERY:
            self._refit_spike()
        if not self._is_spike(step_time_s):
            return None
        if self._since_refit:
            # suspected against stale stats: refit fresh and retest before
            # committing to a dump
            self._refit_spike()
            if not self._is_spike(step_time_s):
                return None
        self.anomalies.append({
            "kind": "step_time_spike", "iteration": self._iteration,
            "step_time_s": float(step_time_s), "median_s": self._spike_med,
            "mad_s": self._spike_sigma / _MAD_SIGMA,
            "threshold_mads": self.spike_mad,
        })
        return self.dump("step_time_spike")

    # -- dumping --------------------------------------------------------------

    def dump(self, reason: str, out_dir: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write the ring to ``<dir>/flightrec-<source>-<reason>-<pid>.json``.

        Directory resolution: explicit arg, then the recorder's ``out_dir``,
        then ``PADDLE_TPU_TELEMETRY_DIR``; with none set the dump is
        skipped (returns None) — the ring stays inspectable in-process.
        Each ``reason`` fires at most once unless ``force``.
        """
        if reason in self._fired and not force:
            return None
        d = out_dir or self.out_dir or telemetry_dir()
        if d is None:
            return None
        self._fired.add(reason)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flightrec-{self.source}-{reason}-{os.getpid()}.json")
        payload = {
            "source": self.source,
            "reason": reason,
            "wall_time": time.time(),
            "iteration": self._iteration,
            "ring_size": self.size,
            "n_records": len(self.ring),
            "anomalies": self.anomalies,
            "records": list(self.ring),
        }
        with open(path, "w") as f:
            json.dump(payload, f, default=_jsonable)
        self.dumped.append(path)
        return path


def load_dump(path: str) -> Dict[str, Any]:
    """Read a flight-recorder dump back (post-mortem tooling/tests)."""
    with open(path) as f:
        return json.load(f)
