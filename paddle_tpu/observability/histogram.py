"""Fixed-memory log-bucketed streaming histograms for live SLO metrics.

The PR-6 engine computed TTFT/TPOT percentiles once, at end of run, from
per-sequence timestamp lists — O(tokens) memory and no live view. A
``LogHistogram`` replaces that with a FIXED array of counters over
log-spaced buckets: ``record()`` is two float ops + one list increment
(no allocation, no device work), ``percentile(q)`` walks the counters,
and the estimate is guaranteed within one bucket of the exact value —
for the default 16 buckets/decade that is a <16% relative error bound,
far inside SLO-dashboard resolution, at a few KB per metric regardless
of traffic.

``render_prometheus`` emits the standard text exposition (cumulative
``_bucket{le=...}`` counts + ``_sum``/``_count``, plain gauges for
scalars) so an operator can scrape an engine snapshot with zero new
dependencies.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Union

__all__ = ["LogHistogram", "render_prometheus", "histogram_sample_lines"]


class LogHistogram:
    """Streaming histogram over log-spaced buckets [lo, hi).

    Bucket ``i`` covers ``lo * 10**(i/bpd) <= v < lo * 10**((i+1)/bpd)``;
    values below ``lo`` (including zero/negative) land in an underflow
    bucket, values ``>= hi`` in an overflow bucket. Exact ``min``/``max``
    /``sum``/``count`` are tracked alongside, and the extreme buckets
    report those exact values, so p0/p100 never invent mass outside the
    observed range.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e4,
                 bins_per_decade: int = 16):
        if lo <= 0 or hi <= lo:
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if bins_per_decade < 1:
            raise ValueError(f"bins_per_decade must be >= 1, "
                             f"got {bins_per_decade}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.bpd = int(bins_per_decade)
        self.n_bins = int(math.ceil(math.log10(hi / lo) * self.bpd))
        # [underflow] + n_bins + [overflow]
        self.counts: List[int] = [0] * (self.n_bins + 2)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # -- recording -----------------------------------------------------------

    def record(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v < self.lo:
            self.counts[0] += 1
        elif v >= self.hi:
            self.counts[-1] += 1
        else:
            idx = int(math.log10(v / self.lo) * self.bpd)
            # float log rounding can land exactly on a boundary; clamp
            idx = min(max(idx, 0), self.n_bins - 1)
            self.counts[idx + 1] += 1

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Fold ``other`` into self (same geometry required)."""
        if (other.lo, other.hi, other.bpd) != (self.lo, self.hi, self.bpd):
            raise ValueError("cannot merge histograms with different "
                             "bucket geometry")
        self.counts = [a + b for a, b in zip(self.counts, other.counts)]
        self.count += other.count
        self.sum += other.sum
        for v in (other.min, other.max):
            if v is None:
                continue
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
        return self

    # -- geometry ------------------------------------------------------------

    def edge(self, i: int) -> float:
        """Lower edge of bucket ``i`` (0..n_bins inclusive -> upper edge)."""
        return self.lo * 10.0 ** (i / self.bpd)

    # -- quantiles -----------------------------------------------------------

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile estimate; None when empty.

        Returns the geometric midpoint of the bucket holding the rank-q
        sample (exact min/max for the under/overflow buckets), clamped to
        the observed [min, max] — within one bucket of the exact order
        statistic by construction.
        """
        if not self.count:
            return None
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        rank = max(1, int(math.ceil(q / 100.0 * self.count)))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i == 0:                       # underflow: exact floor
                    est = self.min
                elif i == len(self.counts) - 1:  # overflow: exact ceiling
                    est = self.max
                else:
                    est = math.sqrt(self.edge(i - 1) * self.edge(i))
                return min(max(est, self.min), self.max)
        return self.max  # unreachable; defensive

    def snapshot(self, quantiles=(50, 90, 99)) -> Dict[str, Optional[float]]:
        """Live summary dict: count/sum/min/max/mean + requested p-quantiles."""
        out: Dict[str, Optional[float]] = {
            "count": self.count, "sum": self.sum,
            "min": self.min, "max": self.max,
            "mean": self.sum / self.count if self.count else None,
        }
        for q in quantiles:
            key = f"p{q:g}".replace(".", "_")
            out[key] = self.percentile(q)
        return out

    def to_dict(self) -> Dict:
        """JSON-ready dump (flight-recorder / JSONL payloads)."""
        return {"lo": self.lo, "hi": self.hi, "bins_per_decade": self.bpd,
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "nonzero_buckets": {str(i): c
                                    for i, c in enumerate(self.counts) if c}}


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_num(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


def histogram_sample_lines(name: str, val: LogHistogram,
                           labels: str = "") -> List[str]:
    """The sample lines of one histogram family (no # comment lines).

    ``labels`` is a pre-rendered ``key="value"`` list (empty for an
    unlabeled family) merged into each ``_bucket`` line ahead of ``le``.
    This is THE bucket-assembly code path: both the legacy dict renderer
    below and ``registry.MetricsRegistry`` call it, so the exposition
    stays byte-identical across the two surfaces.
    """
    sep = labels + "," if labels else ""
    brace = "{" + labels + "}" if labels else ""
    lines: List[str] = []
    # emit only the populated bucket range (plus one flanking
    # bucket each side); the le bounds stay cumulative because the
    # skipped leading buckets are all empty bar underflow, which
    # folds into the first emitted bound
    nz = [i for i in range(1, val.n_bins + 1) if val.counts[i]]
    cum = val.counts[0]
    if nz:
        start = max(1, nz[0] - 1)
        end = min(val.n_bins, nz[-1] + 1)
        for i in range(1, end + 1):
            cum += val.counts[i]
            if i >= start:
                lines.append(
                    f'{name}_bucket{{{sep}le="{_prom_num(val.edge(i))}"}}'
                    f" {cum}")
    lines.append(f'{name}_bucket{{{sep}le="+Inf"}} {val.count}')
    lines.append(f"{name}_sum{brace} {_prom_num(val.sum)}")
    lines.append(f"{name}_count{brace} {val.count}")
    return lines


def render_prometheus(metrics: Dict[str, Union[LogHistogram, float, int]],
                      prefix: str = "paddle_tpu") -> str:
    """Prometheus text exposition of a metric dict.

    ``LogHistogram`` values render as histogram families (cumulative
    ``_bucket{le="..."}`` lines over the non-empty prefix of buckets,
    then ``_sum``/``_count``); plain numbers render as gauges. Keys are
    sanitized to Prometheus metric-name characters.
    """
    lines: List[str] = []
    for key in sorted(metrics):
        val = metrics[key]
        name = _prom_name(f"{prefix}_{key}" if prefix else key)
        if isinstance(val, LogHistogram):
            lines.append(f"# TYPE {name} histogram")
            lines.extend(histogram_sample_lines(name, val))
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_prom_num(float(val))}")
        elif val is None:
            continue
        else:
            raise TypeError(f"metric {key!r}: expected LogHistogram or "
                            f"number, got {type(val).__name__}")
    return "\n".join(lines) + "\n"
