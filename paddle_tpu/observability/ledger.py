"""Kernel-level performance attribution: the always-on roofline ledger.

The fourth observability layer (after traces, streaming metrics, and the
fleet view): *where does the step time go, and how far from roofline is
each line?* Promoted from the PR-6 ``bench_step_ledger`` one-off into a
library with three pieces:

1. :class:`RooflineLedger` — itemizes a step into named components.
   Costs come from the ``cost_estimate=`` FLOPs/bytes every ``pallas_call``
   site already declares (``ops._common.kernel_cost_table`` — PTA003
   guarantees coverage) or from the analytic component specs
   (:func:`flagship_component_specs`); the per-platform peak-FLOPs table
   (``metrics.PEAK_FLOPS_TABLE``) and the HBM-bandwidth table below turn
   each line into a compute-/memory-bound classification with an
   achieved-vs-roofline fraction, and whatever the lines don't cover is an
   explicit ``unattributed`` remainder — the 0.38 gap becomes named lines,
   not a guess.
2. :func:`merge_device_trace` — joins ``jax.profiler`` device trace
   events against host-side chrome spans through the shared
   ``exporters.write_chrome_trace`` writer, one Perfetto view on a common
   clock (host spans + ``comm_span``/``named_scope`` sites + device
   kernel occupancy).
3. The measurement-only contract: nothing here touches the computation —
   model-mode costs are read at TRACE time from the cost-estimate table
   (zero device work), measured-mode components are timed in isolation.
   Losses with the ledger on are bit-identical to off (pinned by test).

Switched by ``PADDLE_TPU_LEDGER`` (+ ``PADDLE_TPU_LEDGER_DIR`` for JSONL
report output); ``jit.TrainStep(ledger=...)`` wins over the env.
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Dict, List, Optional

from .. import envs
from .exporters import write_chrome_trace
from .metrics import peak_flops_info

ENV_LEDGER = "PADDLE_TPU_LEDGER"
ENV_LEDGER_DIR = "PADDLE_TPU_LEDGER_DIR"

# Per-chip HBM bandwidth (bytes/s) by PJRT device_kind substring, matched
# case-insensitively, FIRST match wins (same discipline as
# metrics.PEAK_FLOPS_TABLE). Datasheet numbers — achieved-vs-roofline
# fractions read against these are the conventional (conservative)
# roofline, not the measured-achievable ceiling bench.py's
# measured_hbm_bw() reports. The 'cpu' entry is nominal so virtual-mesh
# runs classify at all.
HBM_BW_TABLE = (
    ("v6e", 1640e9), ("trillium", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5litepod", 819e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
    ("cpu", 50e9),
)


def hbm_bw_per_device(device=None):
    """(bytes/s, source) for one device from the table; (None,
    'unknown:<kind>') when the kind has no entry."""
    if device is None:
        import jax
        devs = jax.devices()
        if not devs:
            return None, "unknown:no-devices"
        device = devs[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, bw in HBM_BW_TABLE:
        if key in kind:
            return bw, f"table:{key}"
    return None, f"unknown:{kind or '?'}"


def ledger_enabled(explicit: Optional[bool] = None) -> bool:
    """Explicit argument wins; else the PADDLE_TPU_LEDGER env knob."""
    if explicit is not None:
        return bool(explicit)
    return bool(envs.get(ENV_LEDGER))


def ledger_dir() -> Optional[str]:
    """Report output directory: PADDLE_TPU_LEDGER_DIR, else the telemetry
    dir so one knob routes all observability files."""
    out = envs.get(ENV_LEDGER_DIR)
    if out:
        return out
    from .trace import telemetry_dir
    return telemetry_dir()


class RooflineLedger:
    """Itemized step-time ledger with per-line roofline classification.

    Two feeding modes, composable in one ledger:

    * **model mode** (the always-on ``TrainStep`` path): ``ingest()`` the
      per-program kernel-cost delta ``ops._common.kernel_costs_since``
      captures while the step lowers — each named pallas_call site becomes
      a line with its declared FLOPs/bytes, and the line's *roofline time*
      (max of compute and memory time at peak) is the attribution. Zero
      device work.
    * **measured mode** (bench / dryrun): ``add(..., time_ms=)`` each
      component timed in isolation (``flagship_component_specs`` provides
      the flagship step's component builders + analytic costs); the line
      then also carries ``achieved_frac`` — roofline time over measured
      time, i.e. how far from the hardware ceiling the component runs.

    ``report(step_time_ms)`` emits the lines plus an explicit
    ``unattributed`` remainder (step time minus attributed time, clamped
    at 0) so the gap is a first-class number, never an implication.
    """

    def __init__(self, name: str = "train_step",
                 peak_flops: Optional[float] = None,
                 hbm_bw: Optional[float] = None,
                 device=None, window: int = 64):
        self.name = name
        if peak_flops is not None:
            self.peak_flops, self.peak_source = float(peak_flops), "arg"
        else:
            self.peak_flops, self.peak_source = peak_flops_info(device)
        if hbm_bw is not None:
            self.hbm_bw, self.bw_source = float(hbm_bw), "arg"
        else:
            self.hbm_bw, self.bw_source = hbm_bw_per_device(device)
        self.components: Dict[str, Dict] = {}
        self._order: List[str] = []
        self.steps = 0
        self._step_ms: collections.deque = collections.deque(maxlen=window)

    # -- feeding -------------------------------------------------------------

    def add(self, name: str, flops: float = 0, bytes_accessed: float = 0,
            transcendentals: float = 0, time_ms: Optional[float] = None,
            calls: int = 1) -> Dict:
        """Add (or replace) one named component line."""
        if name not in self.components:
            self._order.append(name)
        entry = {"flops": float(flops),
                 "bytes_accessed": float(bytes_accessed),
                 "transcendentals": float(transcendentals),
                 "time_ms": time_ms if time_ms is None else float(time_ms),
                 "calls": int(calls)}
        self.components[name] = entry
        return entry

    def ingest(self, costs: Dict[str, Dict]) -> int:
        """Model-mode feed: one line per kernel from a
        ``kernel_costs_since`` delta (or the observed entries of
        ``kernel_cost_table``). Returns the number of lines added."""
        n = 0
        for name, rec in sorted(costs.items()):
            if not rec.get("calls"):
                continue
            self.add(name, flops=rec.get("flops") or 0,
                     bytes_accessed=rec.get("bytes_accessed") or 0,
                     transcendentals=rec.get("transcendentals") or 0,
                     calls=rec["calls"])
            n += 1
        return n

    def on_step(self, step_time_s: float) -> None:
        """Record one measured step wall time (host float, no sync)."""
        self.steps += 1
        if step_time_s and step_time_s > 0:
            self._step_ms.append(step_time_s * 1e3)

    # -- classification ------------------------------------------------------

    def classify(self, flops: float, bytes_accessed: float) -> Dict:
        """Roofline classification of one cost: time at peak compute, time
        at peak bandwidth, whichever dominates is the bound and the max is
        the roofline (best-achievable) time."""
        compute_ms = (flops / self.peak_flops * 1e3
                      if self.peak_flops else None)
        memory_ms = (bytes_accessed / self.hbm_bw * 1e3
                     if self.hbm_bw else None)
        if compute_ms is None and memory_ms is None:
            return {"compute_ms": None, "memory_ms": None,
                    "bound": "unknown", "roofline_ms": None}
        cm, mm = compute_ms or 0.0, memory_ms or 0.0
        return {"compute_ms": compute_ms, "memory_ms": memory_ms,
                "bound": "compute" if cm >= mm else "memory",
                "roofline_ms": max(cm, mm)}

    # -- reporting -----------------------------------------------------------

    def step_time_ms(self) -> Optional[float]:
        """Best recorded step time (best-of mirrors the bench convention:
        jitter is one-sided)."""
        return min(self._step_ms) if self._step_ms else None

    def report(self, step_time_ms: Optional[float] = None) -> Dict:
        """The itemized ledger: one dict per component line, each with its
        roofline classification, plus the explicit unattributed remainder.

        A line's *attributed* time is its measured ``time_ms`` when fed in
        measured mode, else its roofline time (an optimistic floor — real
        kernels run above roofline, so model-mode remainders are upper
        bounds on the true gap)."""
        step_ms = (float(step_time_ms) if step_time_ms is not None
                   else self.step_time_ms())
        lines = []
        attributed = 0.0
        for name in self._order:
            c = self.components[name]
            cls = self.classify(c["flops"], c["bytes_accessed"])
            t = c["time_ms"] if c["time_ms"] is not None \
                else cls["roofline_ms"]
            line = {"name": name, "calls": c["calls"],
                    "flops": c["flops"],
                    "bytes_accessed": c["bytes_accessed"],
                    "transcendentals": c["transcendentals"],
                    "time_ms": c["time_ms"], "attributed_ms": t,
                    "measured": c["time_ms"] is not None}
            line.update(cls)
            if c["time_ms"] and cls["roofline_ms"] is not None \
                    and c["time_ms"] > 0:
                line["achieved_frac"] = cls["roofline_ms"] / c["time_ms"]
            else:
                line["achieved_frac"] = None
            if step_ms and t is not None:
                line["frac_of_step"] = t / step_ms
            else:
                line["frac_of_step"] = None
            attributed += t or 0.0
            lines.append(line)
        out = {"name": self.name, "mode": "ledger",
               "peak_flops": self.peak_flops,
               "peak_source": self.peak_source,
               "hbm_bw": self.hbm_bw, "bw_source": self.bw_source,
               "steps": self.steps, "step_ms": step_ms,
               "attributed_ms": attributed, "lines": lines}
        if step_ms:
            un = max(step_ms - attributed, 0.0)
            out["unattributed_ms"] = un
            out["unattributed_frac"] = un / step_ms
            # the remainder is a LINE, not just a scalar: it renders in
            # the same table and is gated the same way as any component
            lines.append({"name": "unattributed", "calls": 0,
                          "flops": 0.0, "bytes_accessed": 0.0,
                          "transcendentals": 0.0, "time_ms": None,
                          "attributed_ms": un, "measured": False,
                          "compute_ms": None, "memory_ms": None,
                          "bound": "remainder", "roofline_ms": None,
                          "achieved_frac": None,
                          "frac_of_step": un / step_ms})
        else:
            out["unattributed_ms"] = None
            out["unattributed_frac"] = None
        return out

    def report_lines(self, step_time_ms: Optional[float] = None
                     ) -> List[str]:
        """Human-readable rendering of :meth:`report`."""
        rep = self.report(step_time_ms)
        hdr = f"RooflineLedger[{rep['name']}]"
        if rep["step_ms"]:
            hdr += f": step {rep['step_ms']:.3f} ms"
        if rep["unattributed_frac"] is not None:
            hdr += (f", unattributed {rep['unattributed_ms']:.3f} ms "
                    f"({rep['unattributed_frac'] * 100:.1f}%)")
        out = [hdr]
        for ln in rep["lines"]:
            t = ln["attributed_ms"]
            tstr = f"{t:.3f} ms" if t is not None else "?"
            bits = [f"  {ln['name']:<28}{tstr:>12}"]
            if ln["frac_of_step"] is not None:
                bits.append(f"{ln['frac_of_step'] * 100:5.1f}%")
            bits.append(f"[{ln['bound']}]")
            if ln["achieved_frac"] is not None:
                bits.append(f"roofline {ln['achieved_frac'] * 100:.0f}%")
            if not ln["measured"] and ln["bound"] not in ("remainder",):
                bits.append("(model)")
            out.append(" ".join(bits))
        return out

    def write(self, path: Optional[str] = None,
              step_time_ms: Optional[float] = None) -> Optional[str]:
        """Append one report record as a JSONL line (ledger dir default)."""
        if path is None:
            d = ledger_dir()
            if not d:
                return None
            path = os.path.join(d, f"ledger_{self.name}.jsonl")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        from .exporters import _jsonable
        with open(path, "a") as fh:
            fh.write(json.dumps(self.report(step_time_ms),
                                default=_jsonable) + "\n")
        return path


# ---------------------------------------------------------------------------
# flagship component specs (promoted from bench.py's bench_step_ledger)
# ---------------------------------------------------------------------------

def flagship_component_specs(config, batch: int, seq: int,
                             use_flash: bool = True, seed: int = 4):
    """The flagship train step itemized into measured-mode components.

    Returns a list of spec dicts — ``name``, ``build()`` (→ ``(fn, args)``
    to hand to the caller's timer), ``mult`` (L for per-layer components),
    and analytic ``flops`` / ``bytes_accessed`` / ``transcendentals`` for
    the roofline classification — covering attn/ffn/proj/head fwd+bwd,
    the AdamW update, and (zero on one chip) collectives. The caller owns
    the timer (bench.py uses device spans, the dryrun wall clock) and
    feeds ``RooflineLedger.add(name, ..., time_ms=mult * t)``.

    ``use_flash=False`` swaps the attention component to the dense
    (jnp) path for hosts where the Pallas kernels would interpret."""
    import jax as _jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.llama import count_params

    c = config
    B, S, H, I = batch, seq, c.hidden_size, c.intermediate_size
    L, nh, hd = c.num_hidden_layers, c.num_attention_heads, c.head_dim
    V = c.vocab_size
    it = jnp.dtype(c.dtype).itemsize
    rng = np.random.RandomState(seed)
    f = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32) * 0.05,
                               c.dtype)
    sc = 1.0 / (hd ** 0.5)
    cf = 0.5 if use_flash else 1.0  # flash computes the causal half only

    def build_attn_fwd():
        q = f(B * nh, S, hd)
        if use_flash:
            from ..ops import flash_attention as _fa
            fn = lambda q, k, v: _fa._flash_fwd(q, k, v, True, sc,
                                                1024, 1024)[0]
        else:
            def fn(q, k, v):
                s_ = jnp.einsum("bqd,bkd->bqk", q, k) * sc
                mask = jnp.tril(jnp.ones((S, S), bool))
                s_ = jnp.where(mask, s_.astype(jnp.float32), -1e30)
                p = _jax.nn.softmax(s_, axis=-1).astype(q.dtype)
                return jnp.einsum("bqk,bkd->bqd", p, v)
        return fn, (q, q, q)

    def build_attn_bwd():
        fn_f, args = build_attn_fwd()
        loss = lambda *a: (fn_f(*a).astype(jnp.float32) ** 2).sum()
        fn = _jax.grad(loss, argnums=(0, 1, 2))
        return (lambda q, k, v: fn(q, k, v)), args

    def build_ffn_fwd():
        x = f(B * S, H)
        wg, wu, wd = f(H, I), f(H, I), f(I, H)
        fn = lambda x, wg, wu, wd: (_jax.nn.silu(x @ wg) * (x @ wu)) @ wd
        return fn, (x, wg, wu, wd)

    def build_ffn_bwd():
        fn_f, args = build_ffn_fwd()
        loss = lambda *a: (fn_f(*a).astype(jnp.float32) ** 2).sum()
        return _jax.grad(loss, argnums=(0, 1, 2, 3)), args

    def build_proj_fwd():
        x = f(B * S, H)
        wq, wo = f(H, 4 * H), f(H, H)  # fused qkv + q-sized o proj
        fn = lambda x, wq, wo: (x @ wq)[:, :H] @ wo
        return fn, (x, wq, wo)

    def build_proj_bwd():
        fn_f, args = build_proj_fwd()
        loss = lambda *a: (fn_f(*a).astype(jnp.float32) ** 2).sum()
        return _jax.grad(loss, argnums=(0, 1, 2)), args

    labels = jnp.asarray(rng.randint(0, V, (B * S,)), jnp.int32)

    def head_loss(x, wv):
        logits = (x @ wv).astype(jnp.float32)
        return -jnp.take_along_axis(
            _jax.nn.log_softmax(logits, -1), labels[:, None], 1).mean()

    def build_head_fwd():
        return head_loss, (f(B * S, H), f(H, V))

    def build_head_bwd():
        _, args = build_head_fwd()
        return _jax.grad(head_loss, argnums=(0, 1)), args

    P = count_params(c)

    def build_opt():
        p_ = f(P)
        m_ = jnp.zeros((P,), jnp.float32)
        v_ = jnp.zeros((P,), jnp.float32)
        g_ = f(P)

        def adamw(p, m, v, g):
            g32 = g.astype(jnp.float32)
            m2 = 0.9 * m + 0.1 * g32
            v2 = 0.999 * v + 1e-3 * g32 * g32
            return ((p.astype(jnp.float32)
                     - 1e-4 * (m2 / (jnp.sqrt(v2) + 1e-8) + 0.1
                               * p.astype(jnp.float32))).astype(p.dtype),
                    m2, v2)
        return adamw, (p_, m_, v_, g_)

    attn_flops = 2 * 2 * B * nh * S * S * hd * cf
    attn_bytes = 4 * B * nh * S * hd * it
    attn_trans = B * nh * S * S * cf
    ffn_flops = 3 * 2 * B * S * H * I
    ffn_bytes = (2 * B * S * H + 2 * B * S * I + 3 * H * I) * it
    proj_flops = 2 * B * S * H * (4 * H) + 2 * B * S * H * H
    proj_bytes = (B * S * 6 * H + 5 * H * H) * it
    head_flops = 2 * B * S * H * V
    head_bytes = (B * S * H + H * V) * it + 4 * B * S * V
    # AdamW streams bf16 param + f32 m/v in AND out; elementwise FLOPs
    opt_bytes = 2 * P * (it + 4 + 4)
    return [
        {"name": "attention_fwd", "build": build_attn_fwd, "mult": L,
         "flops": attn_flops, "bytes_accessed": attn_bytes,
         "transcendentals": attn_trans},
        {"name": "attention_bwd", "build": build_attn_bwd, "mult": L,
         # bwd recomputes p and runs 5 matmuls vs the fwd's 2
         "flops": 2.5 * attn_flops, "bytes_accessed": 2 * attn_bytes,
         "transcendentals": attn_trans},
        {"name": "ffn_fwd", "build": build_ffn_fwd, "mult": L,
         "flops": ffn_flops, "bytes_accessed": ffn_bytes,
         "transcendentals": B * S * I},
        {"name": "ffn_bwd", "build": build_ffn_bwd, "mult": L,
         "flops": 2 * ffn_flops, "bytes_accessed": 2 * ffn_bytes,
         "transcendentals": B * S * I},
        {"name": "qkvo_proj_fwd", "build": build_proj_fwd, "mult": L,
         "flops": proj_flops, "bytes_accessed": proj_bytes,
         "transcendentals": 0},
        {"name": "qkvo_proj_bwd", "build": build_proj_bwd, "mult": L,
         "flops": 2 * proj_flops, "bytes_accessed": 2 * proj_bytes,
         "transcendentals": 0},
        {"name": "lm_head_loss_fwd", "build": build_head_fwd, "mult": 1,
         "flops": head_flops, "bytes_accessed": head_bytes,
         "transcendentals": B * S * V},
        {"name": "lm_head_loss_bwd", "build": build_head_bwd, "mult": 1,
         "flops": 2 * head_flops, "bytes_accessed": 2 * head_bytes,
         "transcendentals": B * S * V},
        {"name": "optimizer", "build": build_opt, "mult": 1,
         "flops": 10 * P, "bytes_accessed": opt_bytes,
         "transcendentals": P},
    ]


# ---------------------------------------------------------------------------
# device-trace merge
# ---------------------------------------------------------------------------

_HOST_PID = 9000  # host streams re-pid'd above any real device pid


def load_device_trace_events(profile_dir: str) -> List[Dict]:
    """All chrome trace events from a ``jax.profiler.trace`` output tree
    (``**/*.trace.json.gz`` + plain ``.trace.json``)."""
    events: List[Dict] = []
    paths = (glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                       recursive=True)
             + glob.glob(os.path.join(profile_dir, "**", "*.trace.json"),
                         recursive=True))
    for fpath in sorted(paths):
        opener = gzip.open if fpath.endswith(".gz") else open
        try:
            with opener(fpath, "rt") as fh:
                tr = json.load(fh)
        except (OSError, ValueError):
            continue
        events.extend(tr.get("traceEvents") or [])
    return events


def merge_device_trace(profile_dir: str, host_events=None,
                       out_path: Optional[str] = None,
                       align_on: Optional[str] = None) -> Dict:
    """One Perfetto view: device kernel occupancy + host spans, common clock.

    ``profile_dir`` is a ``jax.profiler.trace`` output directory;
    ``host_events`` an iterable of chrome trace-event dicts (µs timebase)
    from any of the repo's host-side producers (``Profiler.export``,
    ``RequestTracer.to_chrome_events``, hand-built spans around step
    components). Device and host streams carry unrelated clocks, so both
    are shifted to a common zero: when ``align_on`` names a span present
    in BOTH streams (e.g. a ``jax.named_scope`` annotation that shows up
    in the device trace's XLA-op metadata and as a host span), the first
    occurrence on each side is pinned to the same instant; otherwise each
    stream's earliest timestamped event becomes t=0 (min-ts alignment —
    coarser, but ordering within each stream is exact).

    Host events are re-pid'd to a dedicated ``host`` process row so they
    never collide with device pids. Writes through the shared
    ``write_chrome_trace`` writer and returns a summary dict."""
    device_events = load_device_trace_events(profile_dir)
    host_events = list(host_events or [])

    def first_ts(evts, name=None):
        ts = [e["ts"] for e in evts
              if e.get("ts") is not None and e.get("ph") != "M"
              and (name is None or name in str(e.get("name", "")))]
        return min(ts) if ts else None

    aligned_on = None
    d0 = h0 = None
    if align_on:
        d0 = first_ts(device_events, align_on)
        h0 = first_ts(host_events, align_on)
        if d0 is not None and h0 is not None:
            aligned_on = align_on
    if aligned_on is None:
        d0 = first_ts(device_events)
        h0 = first_ts(host_events)

    merged: List[Dict] = [
        {"name": "process_name", "ph": "M", "pid": _HOST_PID,
         "args": {"name": "host (paddle_tpu spans)"}},
    ]
    for e in host_events:
        e = dict(e)
        e["pid"] = _HOST_PID + int(e.get("pid", 0) or 0)
        if e.get("ts") is not None and h0 is not None:
            e["ts"] = e["ts"] - h0
        merged.append(e)
    for e in device_events:
        e = dict(e)
        if e.get("ts") is not None and d0 is not None:
            e["ts"] = e["ts"] - d0
        merged.append(e)
    out = {"device_events": len(device_events),
           "host_events": len(host_events),
           "aligned_on": aligned_on,
           "out_path": None}
    if out_path:
        out["out_path"] = write_chrome_trace(out_path, merged)
    else:
        out["events"] = merged
    return out
