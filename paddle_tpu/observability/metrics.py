"""Step-level telemetry: wall step time, compile time, tokens/sec, MFU.

Design constraint: NOTHING here may synchronize the device. Step wall time is
the host-side interval between consecutive ``step()`` calls (in steady state
with donated buffers the dispatch of step N+1 cannot run ahead of step N's
completion, so the interval converges to true device step time without any
``block_until_ready``); memory stats come from the PJRT host-side
``device.memory_stats()`` query; FLOPs are captured once per compile from the
program's cost analysis, not per step. MFU is FLOPs-per-step over
(step_time x peak FLOPs of the slice), the paper's target metric.
"""
from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional

import jax

from .. import envs
from . import trace as _trace
from .histogram import LogHistogram

ENV_PEAK_FLOPS = "PADDLE_TPU_PEAK_FLOPS"

# Per-chip peak FLOP/s by PJRT device_kind substring (bf16 with int8-free
# MXU peaks, the denominators MFU papers use). Matched case-insensitively,
# FIRST match wins, so the more specific names come first. The 'cpu' entry
# is a nominal 100 GFLOP/s per virtual device so virtual-mesh runs report a
# finite (clearly-labeled-estimate) MFU; override with PADDLE_TPU_PEAK_FLOPS.
PEAK_FLOPS_TABLE = (
    ("v6e", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 100e9),
)


# device_kinds already warned about this process: an unknown platform must
# not fall back SILENTLY (roofline/MFU fractions would be quietly wrong or
# quietly absent), but it must also not spam one warning per StepMetrics
_PEAK_WARNED: set = set()


def peak_flops_info(device=None):
    """(per-device peak FLOP/s, source) — source is ``"env"`` (the
    PADDLE_TPU_PEAK_FLOPS override), ``"table:<key>"`` (the device_kind
    row that matched), or ``"unknown:<kind>"`` with a once-per-run warning
    NAMING the platform so an MFU/roofline gap is never a silent None."""
    env = envs.get(ENV_PEAK_FLOPS)
    if env is not None:
        return env, "env"
    if device is None:
        devs = jax.devices()
        if not devs:
            return None, "unknown:no-devices"
        device = devs[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, flops in PEAK_FLOPS_TABLE:
        if key in kind:
            return flops, f"table:{key}"
    if kind not in _PEAK_WARNED:
        _PEAK_WARNED.add(kind)
        import warnings
        warnings.warn(
            f"no peak-FLOPs table entry for device_kind {kind!r}: MFU and "
            f"roofline fractions will be unavailable for this platform — "
            f"set PADDLE_TPU_PEAK_FLOPS or extend "
            f"observability.metrics.PEAK_FLOPS_TABLE", stacklevel=2)
    return None, f"unknown:{kind or '?'}"


def peak_flops_per_device(device=None) -> Optional[float]:
    """Peak FLOP/s for one device, from ``PADDLE_TPU_PEAK_FLOPS`` (wins) or
    the device_kind table; None (with a once-per-run warning via
    :func:`peak_flops_info`) when the kind is unknown."""
    return peak_flops_info(device)[0]


class StepMetrics:
    """Per-step telemetry collector (the xplane-pipeline-shaped summary view).

    Typical wiring (``jit.TrainStep`` does this when telemetry is on)::

        m = StepMetrics(n_devices=mesh.size)
        m.attach(JsonlWriter(path))
        m.record_compile(compile_s=..., trace_s=..., flops=...)   # per compile
        m.step(tokens=B * S)                                      # per step

    ``step()`` builds one record dict, appends it to a bounded window, and
    hands it to every attached exporter. ``summary()`` aggregates the window
    and folds in the trace-time comm counters (hop counts, bucket bytes,
    overlap flags).
    """

    def __init__(self, name: str = "train", tokens_per_step: Optional[int] = None,
                 n_devices: Optional[int] = None,
                 peak_flops: Optional[float] = None, window: int = 512):
        self.name = name
        self.tokens_per_step = tokens_per_step
        self.n_devices = n_devices if n_devices is not None else jax.device_count()
        if peak_flops is not None:
            per_dev, self.mfu_peak_source = peak_flops, "arg"
        else:
            per_dev, self.mfu_peak_source = peak_flops_info()
        self.peak_flops_total = (per_dev * self.n_devices
                                 if per_dev is not None else None)
        self.flops_per_step: Optional[float] = None
        self.compile_time_s = 0.0
        self.trace_time_s = 0.0
        self.compiles = 0
        self.recompiles = 0  # compiles beyond the first
        self.steps = 0
        self.records: collections.deque = collections.deque(maxlen=window)
        # full-run step-time distribution at fixed memory (the bounded
        # records window only covers the last `window` steps); seconds,
        # 10 µs .. 10 ks span
        self.step_time_hist = LogHistogram(lo=1e-5, hi=1e4)
        self._last_t: Optional[float] = None
        self._exporters: List = []
        self._mem_fams = None  # (in_use, peak) gauge families, per device

    def register_into(self, registry) -> None:
        """Expose this collector through a :class:`MetricsRegistry`: the
        full-run step-time histogram (by reference), compile accounting
        gauges, and per-device memory gauge families keyed ``device=``
        (refreshed on every :meth:`device_memory` poll, i.e. each step)."""
        registry.summary("step_time_seconds", hist=self.step_time_hist,
                         help="training step wall time (steady-state "
                              "dispatch interval)")
        registry.gauge("steps", fn=lambda: self.steps,
                       help="steps recorded this run")
        registry.gauge("compiles", fn=lambda: self.compiles,
                       help="program (re)compilations observed")
        registry.gauge("recompiles", fn=lambda: self.recompiles,
                       help="compilations beyond the first")
        registry.gauge("compile_time_seconds",
                       fn=lambda: self.compile_time_s,
                       help="cumulative wall time spent compiling")
        self._mem_fams = (
            registry.family("device_mem_bytes_in_use", "gauge",
                            labelnames=("device",),
                            help="live HBM bytes per local device"),
            registry.family("device_mem_peak_bytes_in_use", "gauge",
                            labelnames=("device",),
                            help="peak HBM bytes per local device"))

    # -- wiring -------------------------------------------------------------

    def attach(self, exporter) -> "StepMetrics":
        """Attach an exporter with a ``write(record: dict)`` method."""
        self._exporters.append(exporter)
        return self

    def close(self) -> None:
        for e in self._exporters:
            try:
                e.close()
            except Exception:
                pass

    # -- recording ----------------------------------------------------------

    def record_compile(self, compile_s: float = 0.0, trace_s: float = 0.0,
                       flops: Optional[float] = None) -> None:
        """One (re)compilation: wall compile/trace seconds and, when known,
        the program's cost-analysis FLOPs per executed step."""
        self.compiles += 1
        if self.compiles > 1:
            self.recompiles += 1
        self.compile_time_s += float(compile_s)
        self.trace_time_s += float(trace_s)
        if flops:
            self.flops_per_step = float(flops)
        # a compile step's wall time is compile, not execution: restart the
        # steady-state interval clock
        self._last_t = None

    def device_memory(self) -> Dict:
        """Host-side PJRT memory stats over ALL ``jax.local_devices()``
        (no sync; {} on backends like CPU that report none). The scalar
        roll-ups keep the pre-PR-15 record keys — ``mem_bytes_in_use``
        is now the SUM across local devices and ``mem_peak_bytes_in_use``
        the max — while ``mem_per_device`` carries each device's stats
        (the devices[0]-only sampling hid every non-0 device's headroom).
        When registered into a MetricsRegistry the per-device values also
        refresh the ``device=``-labeled gauge families."""
        per_dev = []
        try:
            devices = jax.local_devices()
        except Exception:
            devices = []
        for i, dev in enumerate(devices):
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            per_dev.append({"device": i,
                            "bytes_in_use": stats.get("bytes_in_use"),
                            "peak_bytes_in_use":
                                stats.get("peak_bytes_in_use")})
        if not per_dev:
            return {}
        if self._mem_fams is not None:
            fam_use, fam_peak = self._mem_fams
            for e in per_dev:
                if e["bytes_in_use"] is not None:
                    fam_use.labels(device=str(e["device"])).set(
                        e["bytes_in_use"])
                if e["peak_bytes_in_use"] is not None:
                    fam_peak.labels(device=str(e["device"])).set(
                        e["peak_bytes_in_use"])
        in_use = [e["bytes_in_use"] for e in per_dev
                  if e["bytes_in_use"] is not None]
        peaks = [e["peak_bytes_in_use"] for e in per_dev
                 if e["peak_bytes_in_use"] is not None]
        return {"mem_bytes_in_use": sum(in_use) if in_use else None,
                "mem_peak_bytes_in_use": max(peaks) if peaks else None,
                "mem_per_device": per_dev}

    def mfu(self, step_time_s: Optional[float]) -> Optional[float]:
        if (not step_time_s or step_time_s <= 0 or not self.flops_per_step
                or not self.peak_flops_total):
            return None
        return self.flops_per_step / (step_time_s * self.peak_flops_total)

    def step(self, step_time_s: Optional[float] = None,
             tokens: Optional[int] = None, **extra) -> Dict:
        """Record one training step. With no explicit ``step_time_s`` the
        steady-state interval since the previous ``step()`` call is used
        (None on the first step after a (re)compile — no fake numbers)."""
        now = time.perf_counter()
        if step_time_s is None and self._last_t is not None:
            step_time_s = now - self._last_t
        self._last_t = now
        self.steps += 1
        if step_time_s is not None:
            self.step_time_hist.record(step_time_s)
        tokens = tokens if tokens is not None else self.tokens_per_step
        rec: Dict = {
            "name": self.name,
            "step": self.steps,
            "step_time_ms": (step_time_s * 1e3
                             if step_time_s is not None else None),
            "tokens": tokens,
            "tokens_per_sec": (tokens / step_time_s
                               if tokens and step_time_s else None),
            "mfu": self.mfu(step_time_s),
            # provenance of the MFU denominator, so a reader of the JSONL
            # can tell a table-backed fraction from an env override or an
            # unknown-platform None at a glance
            "mfu_peak_source": self.mfu_peak_source,
        }
        rec.update(self.device_memory())
        rec.update(extra)
        self.records.append(rec)
        for e in self._exporters:
            e.write(rec)
        return rec

    # -- aggregation --------------------------------------------------------

    def summary(self) -> Dict:
        """Aggregate view: timing stats over the window, compile accounting,
        MFU at the best step time, and the trace-time comm counters."""
        times = [r["step_time_ms"] for r in self.records
                 if r.get("step_time_ms")]
        best = min(times) if times else None
        mean = sum(times) / len(times) if times else None
        toks = [r["tokens_per_sec"] for r in self.records
                if r.get("tokens_per_sec")]
        out: Dict = {
            "name": self.name,
            "steps": self.steps,
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "compile_time_s": self.compile_time_s,
            "trace_time_s": self.trace_time_s,
            "flops_per_step": self.flops_per_step,
            "peak_flops_total": self.peak_flops_total,
            "mfu_peak_source": self.mfu_peak_source,
            "n_devices": self.n_devices,
            "step_time_ms_best": best,
            "step_time_ms_mean": mean,
            "tokens_per_sec_best": max(toks) if toks else None,
            "mfu_best": self.mfu(best / 1e3) if best else None,
        }
        # streaming (full-run, fixed-memory) step-time distribution —
        # the window stats above forget everything past `window` steps
        if self.step_time_hist.count:
            for q in (50, 90, 99):
                p = self.step_time_hist.percentile(q)
                out[f"step_time_ms_p{q}"] = p * 1e3 if p is not None else None
        out.update(self.device_memory())
        try:
            out["overlap"] = _trace.overlap_flags()
        except Exception:
            pass
        out["counters"] = _trace.counters()
        return out

    def summary_lines(self) -> List[str]:
        """Human-readable summary (the Profiler.summary telemetry section)."""
        s = self.summary()
        lines = [f"StepMetrics[{self.name}]: {s['steps']} steps, "
                 f"{s['compiles']} compiles ({s['recompiles']} re), "
                 f"compile {s['compile_time_s']:.2f}s"]
        if s["step_time_ms_best"] is not None:
            lines.append(
                f"  step time best {s['step_time_ms_best']:.2f} ms / "
                f"mean {s['step_time_ms_mean']:.2f} ms")
        if s["tokens_per_sec_best"]:
            lines.append(f"  tokens/sec best {s['tokens_per_sec_best']:.0f}")
        if s["mfu_best"] is not None:
            lines.append(f"  MFU best {s['mfu_best'] * 100:.2f}% "
                         f"({s['flops_per_step']:.3g} FLOPs/step over "
                         f"{s['peak_flops_total']:.3g} peak FLOP/s)")
        cnt = s.get("counters") or {}
        for key in sorted(cnt):
            lines.append(f"  {key}: {cnt[key]:.0f}")
        for key, val in (s.get("overlap") or {}).items():
            lines.append(f"  {key}: {val}")
        return lines


_active: Optional[StepMetrics] = None


def set_active(metrics: Optional[StepMetrics]) -> None:
    """Install the process-wide collector ``Profiler.summary()`` reports."""
    global _active
    _active = metrics


def active() -> Optional[StepMetrics]:
    return _active
