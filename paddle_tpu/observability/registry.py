"""Unified metrics registry: one Prometheus exposition for the repo.

Before PR 15 every surface assembled its own text: the serving engine
fed a snapshot dict through ``histogram.render_prometheus``, StepMetrics
had no exposition at all, and the fleet layer was about to grow a third.
``MetricsRegistry`` is the single code path: counters, gauges (stored
value or a zero-argument callable read at render time), LogHistogram-
backed summaries, and labeled families of any of those, all rendered by
one ``render_prometheus()`` that emits spec-compliant ``# HELP``/
``# TYPE`` comment pairs ahead of each family's samples, escapes label
values, and keeps histogram ``le`` buckets cumulative (the bucket
assembly is shared with the legacy dict renderer via
``histogram.histogram_sample_lines``, so engine output stayed
byte-identical modulo the comment lines — pinned by a golden test).

Registering the same metric name twice raises: silent shadowing is how
two subsystems end up scraping each other's numbers.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from .histogram import (LogHistogram, _prom_name, _prom_num,
                        histogram_sample_lines)

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Summary", "Family"]

_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape_label(value: str) -> str:
    return "".join(_LABEL_ESCAPES.get(ch, ch) for ch in str(value))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(labelnames: Sequence[str],
                   labelvalues: Sequence[str]) -> str:
    return ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in zip(labelnames, labelvalues))


class Counter:
    """Monotone counter. ``inc()`` only goes up; negative deltas raise."""

    kind = "counter"

    def __init__(self) -> None:
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increments must be >= 0, "
                             f"got {amount}")
        self._value += float(amount)

    def get(self) -> float:
        return self._value


class Gauge:
    """Settable scalar, or a live view over ``fn()`` read at render time."""

    kind = "gauge"

    def __init__(self, fn: Optional[Callable[[], Union[int, float]]] = None):
        self._fn = fn
        self._value: Union[int, float] = 0.0

    def set(self, value: Union[int, float]) -> None:
        if self._fn is not None:
            raise ValueError("callback gauge cannot be set()")
        self._value = value

    def inc(self, amount: float = 1.0) -> None:
        self.set(self._value + amount)

    def get(self) -> Union[int, float]:
        return self._fn() if self._fn is not None else self._value


class Summary:
    """LogHistogram-backed distribution (rendered as a histogram family).

    Pass ``hist=`` to expose an EXISTING LogHistogram by reference (the
    engine's live SLO histograms register this way — zero double
    bookkeeping), or omit it for a fresh one with the given geometry.
    """

    kind = "histogram"

    def __init__(self, hist: Optional[LogHistogram] = None,
                 lo: float = 1e-4, hi: float = 1e4,
                 bins_per_decade: int = 16):
        self.hist = hist if hist is not None else LogHistogram(
            lo=lo, hi=hi, bins_per_decade=bins_per_decade)

    def observe(self, value: float) -> None:
        self.hist.record(value)

    def get(self) -> LogHistogram:
        return self.hist


_FACTORIES = {"counter": Counter, "gauge": Gauge, "histogram": Summary}


class Family:
    """A labeled family: one metric name, one child per label-value set.

    >>> fam = registry.family("hop_ms", "gauge", labelnames=("site",))
    >>> fam.labels(site="tp_ring").set(3.2)

    Children are created on first use and keyed by their label values in
    ``labelnames`` order; every sample line carries the escaped labels.
    """

    def __init__(self, name: str, kind: str, labelnames: Sequence[str],
                 help: str = ""):
        if kind not in _FACTORIES:
            raise ValueError(f"unknown metric kind {kind!r}")
        if not labelnames:
            raise ValueError("a Family needs at least one label name")
        for ln in labelnames:
            if _prom_name(ln) != ln:
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"family {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}")
        key = tuple(str(labelvalues[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _FACTORIES[self.kind]()
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class MetricsRegistry:
    """Named registry of metrics with one Prometheus text exposition.

    Metric names are registered WITHOUT the prefix; ``render_prometheus``
    prepends ``prefix_`` and sanitizes. Families iterate in sorted-name
    order interleaved with scalar metrics, matching the legacy dict
    renderer's ``sorted(keys)`` order so migrated surfaces keep their
    line order.
    """

    def __init__(self, prefix: str = "paddle_tpu"):
        self.prefix = prefix
        self._metrics: Dict[str, Union[Counter, Gauge, Summary, Family]] = {}
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------------

    def _register(self, name: str, metric, help: str):
        with self._lock:
            if name in self._metrics:
                raise ValueError(
                    f"duplicate metric registration: {name!r} is already "
                    f"a {self._metrics[name].kind}")
            self._metrics[name] = metric
            self._help[name] = help
        return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(name, Counter(), help)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], Union[int, float]]] = None) -> Gauge:
        return self._register(name, Gauge(fn=fn), help)

    def summary(self, name: str, help: str = "",
                hist: Optional[LogHistogram] = None, lo: float = 1e-4,
                hi: float = 1e4, bins_per_decade: int = 16) -> Summary:
        return self._register(
            name, Summary(hist=hist, lo=lo, hi=hi,
                          bins_per_decade=bins_per_decade), help)

    def family(self, name: str, kind: str, labelnames: Sequence[str],
               help: str = "") -> Family:
        return self._register(name, Family(name, kind, labelnames, help),
                              help)

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Plain dict view in registration order: LogHistograms for
        summaries, current numbers for counters/gauges (callback gauges
        are invoked), ``{labelvalues: value}`` sub-dicts for families.
        The engine's ``metrics_snapshot()`` is exactly this."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, object] = {}
        for name, m in items:
            if isinstance(m, Family):
                out[name] = {k: (c.get() if not isinstance(c, Summary)
                                 else c.hist)
                             for k, c in m.children()}
            else:
                out[name] = m.get()
        return out

    @classmethod
    def merge(cls, registries: Sequence[Tuple[str, "MetricsRegistry"]],
              label: str = "replica") -> str:
        """Render N registries into ONE Prometheus text block with a
        ``label=`` sample label distinguishing the sources — the fleet
        exposition (PR 20): each serving replica's engine registry
        merges into one scrape carrying ``replica="i"`` on every
        sample line (histogram buckets via the shared PR-15 assembler,
        so per-replica bucket lines stay byte-compatible with a lone
        engine's, modulo the added label).

        The same metric name appearing in several registries is
        label-split — that IS the merge. The same name with a DIFFERENT
        kind or help text is a collision and raises: silent shadowing
        is how two subsystems end up scraping each other's numbers.
        Duplicate label values raise for the same reason.
        """
        if _prom_name(label) != label:
            raise ValueError(f"invalid label name {label!r}")
        decls: Dict[str, Tuple[str, str]] = {}   # full -> (kind, help)
        samples: Dict[str, List[str]] = {}
        seen_values: set = set()
        for value, reg in registries:
            value = str(value)
            if value in seen_values:
                raise ValueError(
                    f"merge(): duplicate {label} label value {value!r}")
            seen_values.add(value)
            with reg._lock:
                items = list(reg._metrics.items())
                helps = dict(reg._help)
            lab = f'{label}="{_escape_label(value)}"'
            for name, m in items:
                full = _prom_name(f"{reg.prefix}_{name}" if reg.prefix
                                  else name)
                help_text = helps.get(name, "")
                if full in decls:
                    kind0, help0 = decls[full]
                    if kind0 != m.kind or help0 != help_text:
                        raise ValueError(
                            f"merge(): metric {full!r} collides across "
                            f"registries ({kind0!r}/{help0!r} vs "
                            f"{m.kind!r}/{help_text!r}); only identical "
                            f"declarations label-split")
                else:
                    decls[full] = (m.kind, help_text)
                out = samples.setdefault(full, [])
                if isinstance(m, Family):
                    if label in m.labelnames:
                        raise ValueError(
                            f"merge(): family {full!r} already carries "
                            f"a {label!r} label")
                    for values_, child in m.children():
                        labels = lab + "," + _render_labels(
                            m.labelnames, values_)
                        if isinstance(child, Summary):
                            out.extend(histogram_sample_lines(
                                full, child.hist, labels=labels))
                        else:
                            out.append(
                                f"{full}{{{labels}}} "
                                f"{_prom_num(float(child.get()))}")
                elif isinstance(m, Summary):
                    out.extend(histogram_sample_lines(full, m.hist,
                                                      labels=lab))
                else:
                    v = m.get()
                    if v is None:
                        continue
                    out.append(f"{full}{{{lab}}} "
                               f"{_prom_num(float(v))}")
        lines: List[str] = []
        for full in sorted(decls):
            kind, help_text = decls[full]
            if help_text:
                lines.append(f"# HELP {full} {_escape_help(help_text)}")
            lines.append(f"# TYPE {full} {kind}")
            lines.extend(samples[full])
        return "\n".join(lines) + "\n"

    def render_prometheus(self) -> str:
        """The single text exposition: per family (sorted by name), a
        ``# HELP`` line (when help text was given), the ``# TYPE`` line,
        then the samples — scalar, labeled, or cumulative-``le``
        histogram lines via the shared bucket assembler."""
        with self._lock:
            items = sorted(self._metrics.items())
            helps = dict(self._help)
        lines: List[str] = []
        for name, m in items:
            full = _prom_name(f"{self.prefix}_{name}" if self.prefix
                              else name)
            if helps.get(name):
                lines.append(f"# HELP {full} {_escape_help(helps[name])}")
            lines.append(f"# TYPE {full} {m.kind}")
            if isinstance(m, Family):
                for values, child in m.children():
                    labels = _render_labels(m.labelnames, values)
                    if isinstance(child, Summary):
                        lines.extend(histogram_sample_lines(
                            full, child.hist, labels=labels))
                    else:
                        lines.append(
                            f"{full}{{{labels}}} "
                            f"{_prom_num(float(child.get()))}")
            elif isinstance(m, Summary):
                lines.extend(histogram_sample_lines(full, m.hist))
            else:
                v = m.get()
                if v is None:
                    continue
                lines.append(f"{full} {_prom_num(float(v))}")
        return "\n".join(lines) + "\n"
