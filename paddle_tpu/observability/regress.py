"""Bench regression ratchet: ``python -m paddle_tpu.observability.regress``.

The PR-11 finding-ratchet pattern applied to performance: a checked-in
baseline (``PERF_BASELINE.json``, seeded from ``BENCH_DETAIL.json``)
freezes one value per bench rung with a per-rung noise band, and
``--check`` compares a fresh bench record against it —

* a rung WORSE than baseline by more than its band **fails**;
* an improvement **passes without moving the baseline** (records only
  ratchet forward deliberately, so a lucky run can't raise the bar);
* a STALE baseline entry (rung missing from the record) **fails** — a
  silently-vanished rung is a lost regression guard, exactly like a
  stale lint-baseline fingerprint;
* a TORN baseline (unparseable, or entries without values) **fails**
  with the defect named;
* moving the baseline requires an explicit ``--accept``.

New rungs in the record are reported but do not fail: new coverage is
not debt. Directions (higher- vs lower-is-better) are derived from the
rung name at seed time and frozen into the baseline entries, so a later
rename cannot silently flip a comparison.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Optional

from .. import envs

ENV_REGRESS_BAND = "PADDLE_TPU_REGRESS_BAND"

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "PERF_BASELINE.json")
DEFAULT_RECORD = os.path.join(_REPO_ROOT, "BENCH_DETAIL.json")

# Rung-name patterns whose value gets BETTER as it goes DOWN. Everything
# else defaults to higher-is-better; booleans are pinned-true gates.
_LOWER_SUFFIXES = ("_ms", "_s", "_pct", "_x_floor")
_LOWER_SUBSTRINGS = ("pad_waste", "overhead", "wire_ratio",
                     "decode_ms_ratio", "unattributed")


def direction(rung: str, value=None) -> str:
    """'bool' | 'lower' | 'higher' for one rung name/value."""
    if isinstance(value, bool):
        return "bool"
    if rung.endswith(_LOWER_SUFFIXES):
        return "lower"
    if any(s in rung for s in _LOWER_SUBSTRINGS):
        return "lower"
    return "higher"


def default_band(rung: str, band: float) -> float:
    """Per-rung noise band at seed time: raw timing/throughput rungs are
    far noisier (host scheduling, CPU-interpret paths) than efficiency
    fractions or attributed-overhead gates, so they seed with a wide
    band; everything else takes the configured default."""
    noisy = ("tokens_per_sec", "_tps", "_ms", "_s", "speedup", "x_floor",
             "hit_rate")
    if rung.endswith(noisy) or any(s in rung
                                   for s in ("tpot", "ttft",
                                             "eff_ceiling")):
        return max(band, 0.5)
    return band


def band_default() -> float:
    """The fractional noise band when neither the baseline entry nor the
    CLI provides one: PADDLE_TPU_REGRESS_BAND."""
    return float(envs.get(ENV_REGRESS_BAND))


# ---------------------------------------------------------------------------
# rung extraction — the ONE mapping from a bench detail dict to the flat
# {rung: value} record (bench.py's summary line calls this too)
# ---------------------------------------------------------------------------

def rungs_from_bench_detail(doc: Dict) -> Dict:
    """Flat ``{rung_name: value}`` from a bench record — either the short
    summary line (has ``rungs``) or the full ``BENCH_DETAIL.json`` shape
    (has ``detail``; the per-section rung mapping lives here so the bench
    and the ratchet can never disagree about what a rung is)."""
    rungs: Dict = {}
    if doc.get("metric") and doc.get("value") is not None:
        rungs[doc["metric"]] = doc["value"]
    if isinstance(doc.get("rungs"), dict):
        rungs.update(doc["rungs"])
        return rungs
    detail = doc.get("detail") or {}
    if "7b_shape" in detail:
        rungs["7b_mfu"] = detail["7b_shape"]["mfu"]
    if "13b_layer" in detail:
        rungs["13b_mfu"] = detail["13b_layer"]["mfu"]
    if "hd64_shape" in detail:
        rungs["hd64_mfu"] = detail["hd64_shape"]["mfu"]
    if "moe" in detail:
        rungs["moe_active_mfu"] = detail["moe"]["active_mfu"]
    if "moe_dropless" in detail:
        rungs["moe_dropless_active_mfu"] = \
            detail["moe_dropless"]["active_mfu"]
        rungs["moe_dropless_pad_waste"] = \
            detail["moe_dropless"]["pad_waste_frac"]
    if "moe_skew_sweep" in detail:
        mss = detail["moe_skew_sweep"]
        rungs["moe_active_mfu"] = max(rungs.get("moe_active_mfu", 0.0),
                                      mss["active_mfu"])
        rungs["moe_skew_wire_ratio_zipf"] = \
            mss["sweep"]["zipf"]["wire_vs_dense_ratio"]
        if mss.get("overlap_fraction") is not None:
            rungs["moe_a2a_overlap_fraction"] = mss["overlap_fraction"]
    decode = detail.get("decode") or {}
    if "hd64_pair_stack_ab" in decode:
        rungs["decode_hd64_pair_stack_speedup"] = \
            decode["hd64_pair_stack_ab"]["pair_stack_speedup"]
    if "flagship_b8" in decode:
        rungs["decode_flagship_b8_x_floor"] = \
            decode["flagship_b8"]["x_of_floor"]
        if "hd64_b8" in decode:
            rungs["decode_hd64_b8_x_floor"] = \
                decode["hd64_b8"]["x_of_floor"]
    if "long_seq_flash_fwd" in detail:
        ls = detail["long_seq_flash_fwd"]
        for s_key, tag in (("S16384", "16k"), ("S32768", "32k"),
                           ("S131072", "128k")):
            if s_key in ls:
                rungs[f"flash_fwd_eff_{tag}"] = ls[s_key]["attn_eff"]
                rungs[f"flash_bwd_eff_{tag}"] = ls[s_key]["bwd_eff"]
    if "packed_varlen_16seq_16k" in detail:
        pv = detail["packed_varlen_16seq_16k"]
        rungs["varlen_fwd_eff"] = pv["varlen_fwd_eff"]
        rungs["varlen_bwd_eff"] = pv["varlen_bwd_eff"]
        ca = pv.get("ceiling_ablation")
        if ca:
            rungs["varlen_fwd_eff_ceiling"] = ca["varlen_fwd_eff_ceiling"]
            rungs["varlen_bwd_eff_ceiling"] = ca["varlen_bwd_eff_ceiling"]
    if "serve_continuous" in detail:
        sc = detail["serve_continuous"]
        rungs["serve_tokens_per_sec"] = sc["tokens_per_sec"]
        rungs["serve_tpot_p99_s"] = sc["tpot_p99_s"]
    if "serve_overload" in detail:
        so = detail["serve_overload"]
        rungs["serve_overload_goodput_tps"] = so["goodput_tokens_per_sec"]
        rungs["serve_overload_deterministic"] = bool(
            so["shed_deterministic"] and so["streams_identical"]
            and so["no_silent_drops"] and so["pool_leak_free"])
        rungs["serve_admission_journal_pct"] = \
            so["admission_journal_overhead_pct"]
    if "serve_prefix_cache" in detail:
        sp = detail["serve_prefix_cache"]
        rungs["serve_prefix_hit_rate"] = sp["hit_rate"]
        rungs["serve_prefix_ttft_p50_speedup"] = sp["ttft_p50_speedup"]
        rungs["serve_prefix_clean"] = bool(
            sp["cached_tokens_identical"] and sp["pool_leak_free"])
    if "serve_kv_int8" in detail:
        si = detail["serve_kv_int8"]
        rungs["serve_kv_int8_concurrency_x"] = si["concurrency_ratio"]
        rungs["serve_kv_int8_vs_fp16_x"] = si["fp16_equivalent_ratio"]
        rungs["serve_kv_int8_decode_ms_ratio"] = si["decode_ms_ratio"]
    if "serve_speculative" in detail:
        ss = detail["serve_speculative"]
        rungs["serve_spec_accept_rate"] = ss["accept_rate"]
        # iteration-clock speedup vs the sequential engine on the same
        # trace (deterministic mode: both runs replay bit-identically,
        # so the ratio is noise-free by construction)
        rungs["serve_spec_speedup"] = ss["speedup"]
        rungs["serve_spec_parity"] = bool(
            ss["streams_identical"] and ss["pool_leak_free"])
    if "serve_tp" in detail and "streams_identical" in detail["serve_tp"]:
        st = detail["serve_tp"]
        # token-bitwise parity at every sharded degree plus leak-free
        # pools is the gate the feature ships under (PARITY.md)
        rungs["serve_tp_parity"] = bool(
            st["streams_identical"] and st["pool_leak_free"])
        # off-TPU this measures sharding overhead on a time-sliced host
        # (expected < 1); on TPU it is the real mp scaling number
        rungs["serve_tp_speedup"] = st["wall_speedup_top"]
    if "serve_fleet" in detail and "streams_identical" in detail[
            "serve_fleet"]:
        sf = detail["serve_fleet"]
        # the PR-20 ship gate: every fleet size bit-identical to the
        # lone engine, zero lost accepted requests (incl. the chaos
        # kill), leak-free pools, and a rolling swap with zero drops
        rungs["serve_fleet_parity"] = bool(
            sf["streams_identical"] and sf["zero_lost"]
            and sf["pool_leak_free"]
            and sf["chaos_kill"]["lost"] == 0
            and sf["chaos_kill"]["streams_identical"]
            and sf["rolling_swap"]["lost"] == 0
            and sf["rolling_swap"]["drops"] == 0
            and sf["rolling_swap"]["streams_identical"])
        # off-TPU this measures router + replica duplication overhead
        # on a time-sliced host (~1.0); on TPU it is real fleet scaling
        rungs["serve_fleet_speedup"] = sf["wall_speedup_top"]
    if "varlen_ceiling_ablation" in detail:
        # standalone (off-TPU) run of the ceiling rung; on TPU the same
        # rung names come from packed_varlen's ceiling_ablation above
        ca = detail["varlen_ceiling_ablation"]
        rungs["varlen_fwd_eff_ceiling"] = ca["varlen_fwd_eff_ceiling"]
        rungs["varlen_bwd_eff_ceiling"] = ca["varlen_bwd_eff_ceiling"]
    if "fleet_observability" in detail:
        fo = detail["fleet_observability"]
        rungs["fleet_observability_pct"] = fo["fleet_overhead_pct"]
        rungs["fleet_observability_clean"] = bool(
            fo["monitored_losses_identical"] and fo["health_check_ok"])
    if "ledger_roofline" in detail:
        lr = detail["ledger_roofline"]
        rungs["ledger_unattributed_frac"] = lr["unattributed_frac"]
        rungs["ledger_overhead_pct"] = lr["ledger_overhead_pct"]
        rungs["ledger_clean"] = bool(lr["ledger_losses_identical"])
    return rungs


# ---------------------------------------------------------------------------
# baseline I/O
# ---------------------------------------------------------------------------

class TornBaseline(ValueError):
    """The baseline file exists but is not a usable ratchet."""


def load_baseline(path: Optional[str] = None) -> Dict:
    """Parsed baseline, or {} when the file does not exist yet. Raises
    :class:`TornBaseline` naming the defect when the file is torn
    (unparseable JSON, wrong top-level shape, entries missing values)."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except ValueError as e:
        raise TornBaseline(f"{path}: unparseable JSON ({e})")
    entries = data.get("entries")
    if not isinstance(entries, dict):
        raise TornBaseline(f"{path}: no 'entries' mapping")
    for rung, entry in entries.items():
        if not isinstance(entry, dict) or "value" not in entry:
            raise TornBaseline(f"{path}: entry {rung!r} has no value")
        if entry.get("direction") not in ("higher", "lower", "bool"):
            raise TornBaseline(f"{path}: entry {rung!r} has no direction")
    return data


def write_baseline(rungs: Dict, path: Optional[str] = None,
                   band: Optional[float] = None,
                   prev: Optional[Dict] = None,
                   source: str = "BENCH_DETAIL.json") -> Dict:
    """Freeze ``rungs`` as the new baseline. Per-entry ``band`` /
    ``direction`` overrides from a previous baseline survive for rungs
    that persist (an operator-tuned band is deliberate state)."""
    path = path or DEFAULT_BASELINE
    band = band if band is not None else band_default()
    prev_entries = (prev or {}).get("entries") or {}
    entries = {}
    for rung in sorted(rungs):
        value = rungs[rung]
        if value is None:
            continue
        old = prev_entries.get(rung) or {}
        d = old.get("direction") or direction(rung, value)
        entry = {"value": value, "direction": d}
        if d != "bool":
            entry["band"] = old.get("band", default_band(rung, band))
        entries[rung] = entry
    data = {
        "_comment": ("perf ratchet baseline (regress --accept); --check "
                     "fails on rungs worse than value by more than band "
                     "and on stale entries; improvements pass without "
                     "moving this file"),
        "source": source,
        "band_default": band,
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return data


# ---------------------------------------------------------------------------
# the check
# ---------------------------------------------------------------------------

def check(rungs: Dict, baseline: Dict,
          band: Optional[float] = None) -> Dict:
    """Compare one rung record against a loaded baseline.

    Returns ``{ok, regressed, stale, improved, unchanged, new, lines}``;
    ``ok`` is False exactly when ``regressed`` or ``stale`` is non-empty.
    """
    fallback = band if band is not None else \
        baseline.get("band_default", band_default())
    entries = baseline.get("entries") or {}
    regressed, stale, improved, unchanged, new = [], [], [], [], []
    lines = []
    for rung in sorted(entries):
        entry = entries[rung]
        base, d = entry["value"], entry["direction"]
        if rung not in rungs or rungs[rung] is None:
            stale.append(rung)
            lines.append(f"STALE      {rung}: baseline {base} but the "
                         f"record has no such rung (lost guard — re-run "
                         f"the bench or --accept the removal)")
            continue
        val = rungs[rung]
        if d == "bool":
            if bool(base) and not bool(val):
                regressed.append(rung)
                lines.append(f"REGRESSED  {rung}: {base} -> {val}")
            else:
                (unchanged if bool(val) == bool(base)
                 else improved).append(rung)
                lines.append(f"ok         {rung}: {val}")
            continue
        b = entry.get("band", fallback)
        if d == "lower":
            worse = val > base * (1.0 + b)
            better = val < base
        else:
            worse = val < base * (1.0 - b)
            better = val > base
        if worse:
            regressed.append(rung)
            lines.append(f"REGRESSED  {rung}: {base} -> {val} "
                         f"({d} is better, band {b:.0%})")
        elif better:
            improved.append(rung)
            lines.append(f"improved   {rung}: {base} -> {val} "
                         f"(baseline unmoved)")
        else:
            unchanged.append(rung)
            lines.append(f"ok         {rung}: {base} -> {val} "
                         f"(within band {b:.0%})")
    for rung in sorted(set(rungs) - set(entries)):
        if rungs[rung] is None:
            continue
        new.append(rung)
        lines.append(f"new        {rung}: {rungs[rung]} (not in baseline; "
                     f"--accept to start guarding it)")
    return {"ok": not regressed and not stale, "regressed": regressed,
            "stale": stale, "improved": improved, "unchanged": unchanged,
            "new": new, "lines": lines}


def load_record(path: str) -> Dict:
    """Flat rung record from a bench output file: the full
    BENCH_DETAIL.json shape, the short summary-line shape, or an
    already-flat {rung: value} mapping."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if "detail" in doc or "rungs" in doc or "metric" in doc:
        return rungs_from_bench_detail(doc)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.regress",
        description="bench perf regression ratchet")
    ap.add_argument("--check", action="store_true",
                    help="compare the record against the baseline; exit 1 "
                         "on regressions beyond band or stale entries")
    ap.add_argument("--accept", action="store_true",
                    help="move the baseline to the record's values "
                         "(the ONLY way the baseline moves)")
    ap.add_argument("--record", default=DEFAULT_RECORD,
                    help="bench record (default: BENCH_DETAIL.json)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: PERF_BASELINE.json)")
    ap.add_argument("--band", type=float, default=None,
                    help="fractional noise band override (default: "
                         "per-entry band, else PADDLE_TPU_REGRESS_BAND)")
    args = ap.parse_args(argv)
    if not args.check and not args.accept:
        ap.error("one of --check / --accept is required")
    try:
        rungs = load_record(args.record)
    except (OSError, ValueError) as e:
        print(f"regress: cannot read record {args.record}: {e}",
              file=sys.stderr)
        return 2
    if args.accept:
        try:
            prev = load_baseline(args.baseline)
        except TornBaseline:
            prev = {}  # --accept is the repair path for a torn baseline
        data = write_baseline(rungs, args.baseline, band=args.band,
                              prev=prev, source=os.path.basename(
                                  args.record))
        print(f"regress: baseline {args.baseline} <- "
              f"{len(data['entries'])} rungs from {args.record}")
        if not args.check:
            return 0
    try:
        baseline = load_baseline(args.baseline)
    except TornBaseline as e:
        print(f"regress: TORN baseline — {e}", file=sys.stderr)
        return 1
    if not baseline:
        print(f"regress: no baseline at {args.baseline}; seed one with "
              f"--accept", file=sys.stderr)
        return 1
    res = check(rungs, baseline, band=args.band)
    for line in res["lines"]:
        print(line)
    print(f"regress: {len(res['unchanged'])} ok, "
          f"{len(res['improved'])} improved, {len(res['new'])} new, "
          f"{len(res['stale'])} stale, {len(res['regressed'])} regressed "
          f"-> {'PASS' if res['ok'] else 'FAIL'}")
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
