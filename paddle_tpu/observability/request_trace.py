"""Request-lifecycle tracing for the serving engine.

Every engine request gets a trace id and a span tree — queue wait,
admission, each chunked-prefill slice, each decode iteration it
participated in, eviction and re-prefill recompute — recorded entirely
host-side. The engine hands the tracer ``time.perf_counter()`` values it
ALREADY captures at iteration boundaries (``step()``'s phase clocks), so
tracing adds no device syncs and no new clock reads on the hot path, and
never feeds back into scheduling: deterministic replay produces
bit-identical tokens with tracing on or off (pinned by test).

The hot path appends one tuple per event — a decode batch is a SINGLE
tuple carrying the participating rids, expanded to per-request spans
only at query/export time — so recording costs nanoseconds per
iteration and the tokens/s overhead stays under the 2% telemetry bar
even on a tiny interpret-mode model (benchmarks/overlap_bench.py
``bench_serve_overhead``).

Exports:

- ``export_jsonl(path)`` — one span per line for programmatic analysis;
- ``export_chrome(path)`` — Chrome trace-event JSON through the same
  writer the profiler uses (``exporters.write_chrome_trace``), laid out
  so Perfetto renders one row per engine phase (admit/prefill/decode)
  and one row per request, with eviction as instant markers.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from .exporters import JsonlWriter, write_chrome_trace

__all__ = ["RequestTracer", "PHASE_TIDS", "REQUEST_TID_BASE"]

# Perfetto row layout: engine phases on low tids, requests on 10+rid.
PHASE_TIDS = {"admit": 0, "prefill": 1, "decode": 2}
REQUEST_TID_BASE = 10


class RequestTracer:
    """Span collector for one engine run.

    Internally spans are tuples ``(rid, name, cat, t0, t1, args)`` with
    times in raw ``perf_counter`` seconds; ``rid`` is None for
    engine-phase spans and a tuple of rids for decode batches. Queries
    and exports materialize plain dicts ``{trace_id, rid, name, cat,
    t0, t1, args}`` (decode batches as one span per participant) and
    rebase times onto the earliest timestamp seen so traces start at
    t=0.
    """

    def __init__(self):
        self._spans: List[Tuple] = []
        self._queue_from: Dict[int, float] = {}   # rid -> submit time
        self._chunk_idx: Dict[int, int] = {}      # rid -> prefill chunks so far
        self._epoch: Optional[float] = None

    # -- recording (engine event surface) ------------------------------------

    def _span(self, rid, name: str, cat: str, t0: float, t1: float,
              args: Optional[Dict[str, Any]]) -> None:
        if self._epoch is None or t0 < self._epoch:
            self._epoch = t0
        self._spans.append((rid, name, cat, t0, t1, args))

    def submit(self, rid: int, t: float) -> None:
        """Request entered the waiting queue; opens its queue-wait span."""
        self._queue_from[rid] = t
        if self._epoch is None or t < self._epoch:
            self._epoch = t

    def admit(self, rid: int, t: float, n_preempted: int = 0) -> None:
        """Request admitted: closes the pending queue-wait span."""
        t0 = self._queue_from.pop(rid, t)
        name = "requeue" if n_preempted else "queue"
        self._span(rid, name, "queue", t0, t, {"n_preempted": n_preempted})

    def prefill_chunk(self, rid: int, t0: float, t1: float, n_tokens: int,
                      recompute: bool) -> None:
        """One chunked-prefill slice; ``recompute`` marks post-eviction
        re-prefill of already-generated context."""
        i = self._chunk_idx.get(rid, 0)
        self._chunk_idx[rid] = i + 1
        cat = "reprefill" if recompute else "prefill"
        self._span(rid, f"{cat}[{i}]", cat, t0, t1, {"n_tokens": n_tokens})

    def decode(self, rids: List[int], t0: float, t1: float,
               iteration: int) -> None:
        """One decode batch: a single tuple now, one span per
        participating request's row at export. Inlined append — this is
        the per-iteration hot path."""
        if self._epoch is None or t0 < self._epoch:
            self._epoch = t0
        self._spans.append((tuple(rids), "decode", "decode", t0, t1,
                            {"iteration": iteration, "batch": len(rids)}))

    def evict(self, rid: int, t: float, n_preempted: int) -> None:
        """Preemption: instant marker on the request row, then the request
        waits again (queue-wait span reopens until readmission)."""
        self._span(rid, "evict", "evict", t, t, {"n_preempted": n_preempted})
        self._queue_from[rid] = t

    def finish(self, rid: int, t: float, n_generated: int) -> None:
        self._span(rid, "finish", "finish", t, t,
                   {"n_generated": n_generated})
        self._chunk_idx.pop(rid, None)

    def reject(self, rid: int, t: float, cause: str) -> None:
        """Admission rejection: instant marker — the request never made
        it into the waiting queue, so no queue span opens."""
        self._span(rid, "reject", "reject", t, t, {"cause": cause})

    def shed(self, rid: int, t: float, cause: str) -> None:
        """Deadline shed: closes the request's pending queue-wait span
        with the shed cause (it waited, then the scheduler gave up)."""
        t0 = self._queue_from.pop(rid, t)
        self._span(rid, "shed", "shed", t0, t, {"cause": cause})
        self._chunk_idx.pop(rid, None)

    def quarantine(self, rid: int, t: float, cause: str) -> None:
        """Poison quarantine: instant failure marker on the request row."""
        self._queue_from.pop(rid, None)
        self._span(rid, "quarantine", "quarantine", t, t, {"cause": cause})
        self._chunk_idx.pop(rid, None)

    def phase(self, name: str, t0: float, t1: float, iteration: int) -> None:
        """Engine-phase span (admit/prefill/decode) for one iteration.
        Inlined append — called up to three times per iteration."""
        if t1 > t0:
            if self._epoch is None or t0 < self._epoch:
                self._epoch = t0
            self._spans.append((None, name, "phase", t0, t1,
                                {"iteration": iteration}))

    # -- materialization -------------------------------------------------------

    def _iter_dicts(self) -> Iterator[Dict[str, Any]]:
        """Expand the tuple log into per-request span dicts (decode
        batches fan out to one span per participant)."""
        for rid, name, cat, t0, t1, args in self._spans:
            args = args or {}
            if isinstance(rid, tuple):
                for r in rid:
                    yield {"trace_id": f"req-{r}", "rid": r, "name": name,
                           "cat": cat, "t0": t0, "t1": t1, "args": args}
            else:
                tid = f"req-{rid}" if rid is not None else "engine"
                yield {"trace_id": tid, "rid": rid, "name": name,
                       "cat": cat, "t0": t0, "t1": t1, "args": args}

    @property
    def spans(self) -> List[Dict[str, Any]]:
        """Materialized span dicts (cold path — tests and exports)."""
        return list(self._iter_dicts())

    # -- queries (tests / dryrun asserts) -------------------------------------

    def request_ids(self) -> List[int]:
        out = set()
        for rid, *_ in self._spans:
            if isinstance(rid, tuple):
                out.update(rid)
            elif rid is not None:
                out.add(rid)
        return sorted(out)

    def tree(self, rid: int) -> Dict[str, Any]:
        """Span tree for one request: a root covering its lifetime with the
        time-ordered child spans nested under it."""
        children = sorted((s for s in self._iter_dicts() if s["rid"] == rid),
                          key=lambda s: (s["t0"], s["t1"]))
        if not children:
            raise KeyError(f"no spans recorded for request {rid}")
        return {
            "trace_id": f"req-{rid}",
            "request_id": rid,
            "t0": children[0]["t0"],
            "t1": children[-1]["t1"],
            "children": children,
        }

    # -- export ---------------------------------------------------------------

    def _rel(self, t: float) -> float:
        return t - (self._epoch or 0.0)

    def to_jsonl_records(self) -> List[Dict[str, Any]]:
        recs = []
        for s in sorted(self._iter_dicts(),
                        key=lambda s: (s["t0"], s["t1"])):
            recs.append({
                "trace_id": s["trace_id"], "rid": s["rid"],
                "name": s["name"], "cat": s["cat"],
                "t0_s": self._rel(s["t0"]),
                "dur_s": s["t1"] - s["t0"],
                **s["args"],
            })
        return recs

    def export_jsonl(self, path: str) -> str:
        w = JsonlWriter(path)
        try:
            for rec in self.to_jsonl_records():
                w.write(rec)
        finally:
            w.close()
        return path

    def to_chrome_events(self) -> List[Dict[str, Any]]:
        """Chrome trace-event list: ``M`` thread-name metadata + ``X``
        duration spans (+ ``i`` instants for evict/finish), µs timebase."""
        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu.serve"}},
        ]
        for name, tid in sorted(PHASE_TIDS.items(), key=lambda kv: kv[1]):
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": tid, "args": {"name": f"engine/{name}"}})
        for rid in self.request_ids():
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": REQUEST_TID_BASE + rid,
                           "args": {"name": f"request {rid}"}})
        for s in sorted(self._iter_dicts(),
                        key=lambda s: (s["t0"], s["t1"])):
            if s["rid"] is None:
                tid = PHASE_TIDS.get(s["name"], PHASE_TIDS["decode"])
            else:
                tid = REQUEST_TID_BASE + s["rid"]
            ev = {"name": s["name"], "ts": self._rel(s["t0"]) * 1e6,
                  "pid": 0, "tid": tid, "cat": s["cat"],
                  "args": dict(s["args"])}
            if s["t1"] > s["t0"]:
                ev["ph"] = "X"
                ev["dur"] = (s["t1"] - s["t0"]) * 1e6
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
        return events

    def export_chrome(self, path: str) -> str:
        return write_chrome_trace(path, self.to_chrome_events())

    # -- SLO helper -----------------------------------------------------------

    def span_count(self, cat: Optional[str] = None) -> int:
        """Number of materialized spans (decode batches count once per
        participating request), optionally filtered by category."""
        if cat is None:
            return sum(len(rid) if isinstance(rid, tuple) else 1
                       for rid, *_ in self._spans)
        return sum(1 for s in self._iter_dicts() if s["cat"] == cat)


def spans_overlap(spans: List[Dict[str, Any]]) -> bool:
    """True when any two duration spans in ``spans`` overlap in time —
    sanity helper for per-row layout tests (a request is only ever in one
    engine phase at a time, so its row must be overlap-free)."""
    ivs = sorted((s["t0"], s["t1"]) for s in spans if s["t1"] > s["t0"])
    latest_end = None
    for t0, t1 in ivs:
        if latest_end is not None and t0 < latest_end:
            return True
        latest_end = t1 if latest_end is None else max(latest_end, t1)
    return False
