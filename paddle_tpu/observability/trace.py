"""Trace attribution primitives for the hybrid-parallel hot path.

Two mechanisms, both free at step time:

- ``comm_span(name)`` — a context manager entered while a collective site is
  being TRACED into a jitted program. It pushes a ``jax.named_scope`` (the
  name lands in the HLO op metadata, so XLA's xplane profile attributes the
  device time of that ppermute/psum to the span name in TensorBoard/Perfetto)
  plus a host ``jax.profiler.TraceAnnotation`` so tracing itself shows up in
  host timelines. No code runs per executed step.

- counters — a process-global tally the spans (and planners) bump at trace
  time: ppermute hop counts, grad-sync bucket bytes, overlap on/off. Because
  instrumented code runs when a program is traced, counters are STATIC
  attribution of the compiled step (like HLO op counts), not execution
  counts: a kernel retraced for fwd+bwd or under remat tallies each trace.
  ``reset_counters()`` before building a step and ``counters()`` after gives
  the per-program attribution the StepMetrics collector surfaces.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

import jax

from .. import envs

ENV_TELEMETRY = "PADDLE_TPU_TELEMETRY"
ENV_TELEMETRY_DIR = "PADDLE_TPU_TELEMETRY_DIR"

_TRUTHY = ("1", "true", "on", "yes")


def telemetry_enabled(explicit: Optional[bool] = None) -> bool:
    """Telemetry switch: an explicit argument wins, else ``PADDLE_TPU_TELEMETRY``."""
    if explicit is not None:
        return bool(explicit)
    return envs.get(ENV_TELEMETRY)


def telemetry_dir() -> Optional[str]:
    """Step-log directory from ``PADDLE_TPU_TELEMETRY_DIR`` (None: no file)."""
    return envs.get(ENV_TELEMETRY_DIR)


_counters: Dict[str, float] = {}
_lock = threading.Lock()


def record_counter(name: str, value: float = 1.0) -> None:
    """Add ``value`` to counter ``name`` (creates at 0)."""
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + float(value)


def set_counter(name: str, value: float) -> None:
    with _lock:
        _counters[name] = float(value)


def counters() -> Dict[str, float]:
    """Snapshot of every counter."""
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


@contextlib.contextmanager
def comm_span(name: str, nbytes: Optional[int] = None,
              site: Optional[str] = None):
    """Attribute a collective site: named HLO scope + host trace annotation +
    ``{name}.calls`` / ``{name}.bytes`` counters. Safe inside jit/shard_map/
    scan tracing (where it tallies once per trace) and in eager host code.

    ``site=`` is the STABLE straggler-attribution key (PR 15): unlike
    ``name`` — often per-instance, e.g. ``grad_sync.bucket07`` — the site
    label is a static string shared by every instance of one collective
    family, tallied as ``site.<site>.{calls,bytes,ms}`` counters so the
    FleetMonitor can compare the same site across ranks. The ``.ms``
    tally is host time inside the span (trace time under jit; wall time
    at eager sites like the serve prefill/decode dispatch)."""
    record_counter(name + ".calls", 1)
    if nbytes is not None:
        record_counter(name + ".bytes", int(nbytes))
    if site is not None:
        record_counter(f"site.{site}.calls", 1)
        if nbytes is not None:
            record_counter(f"site.{site}.bytes", int(nbytes))
    ann = None
    try:
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    except Exception:
        ann = None
    t0 = time.perf_counter()
    try:
        with jax.named_scope(name):
            yield
    finally:
        if site is not None:
            record_counter(f"site.{site}.ms",
                           (time.perf_counter() - t0) * 1e3)
        if ann is not None:
            ann.__exit__(None, None, None)


def overlap_flags() -> Dict[str, int]:
    """The PR-1 overlap switches as 0/1 counters (tp ring, pp async-p2p,
    grad-sync mode is per-TrainStep and recorded there)."""
    from ..parallel import collective_matmul as _cm
    from ..parallel import pipeline as _pl
    return {
        "tp.overlap_on": int(_cm.overlap_enabled()),
        "pp.overlap_on": int(_pl.p2p_overlap_enabled()),
    }
