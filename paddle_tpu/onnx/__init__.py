"""paddle.onnx parity (ref: python/paddle/onnx/export.py).

The reference's ``paddle.onnx.export`` is a thin wrapper that REQUIRES the
external ``paddle2onnx`` package and raises if it is missing. This build keeps
the same delegation contract: it always saves the portable StableHLO bundle
(the TPU-native interchange format — same role, compiled by any XLA backend)
and raises pointing ONNX conversion at an external converter.
"""
from __future__ import annotations

import os


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` for interchange.

    Always writes the StableHLO bundle via ``paddle.jit.save`` at ``{path}``,
    then raises (ImportError without the onnx package, NotImplementedError
    with it) directing op-graph ONNX conversion to an external converter —
    the reference behaves the same way about paddle2onnx.
    """
    try:
        import onnx  # noqa: F401
        has_onnx = True
    except ImportError:
        has_onnx = False

    from ..jit.save_load import save as jit_save
    jit_save(layer, path, input_spec=input_spec)

    if not has_onnx:
        raise ImportError(
            "paddle.onnx.export requires the 'onnx' package (the reference "
            "requires 'paddle2onnx' the same way). The model was saved as a "
            f"portable StableHLO bundle at '{path}' — loadable with "
            "paddle.jit.load / paddle.inference.create_predictor on any XLA "
            "backend.")

    return _export_onnx(layer, path, input_spec, opset_version)


def _export_onnx(layer, path, input_spec, opset_version):
    """Full op-graph conversion belongs to an external converter, exactly as
    the reference delegates to paddle2onnx — emitting a structurally-empty
    ModelProto here would be a silent lie, so be explicit instead."""
    raise NotImplementedError(
        "ONNX op-graph conversion is delegated to external converters (the "
        "reference requires paddle2onnx the same way). Use the StableHLO "
        f"bundle saved at '{path}' (paddle.jit.load / "
        "paddle.inference.create_predictor) for deployment.")
