"""Pallas custom kernels replacing the reference's fused CUDA kernels
(ref: paddle/fluid/operators/fused/, paddle/phi/kernels/fusion/)."""
from .flash_attention import flash_attention_bshd
from .rms_norm import fused_rms_norm
from .rope import apply_rope, build_rope_cache, fused_rotary_position_embedding
