"""Shared kernel plumbing."""
from __future__ import annotations

import jax

_FORCE_INTERPRET = None


def set_interpret(value: bool | None):
    """Override interpret-mode detection (None = auto)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = value


def mosaic_trace_ctx():
    """Trace Pallas kernels with x64 off: the package enables jax_enable_x64
    globally (Paddle dtype semantics), but Mosaic cannot legalize the 64-bit
    index/constant types that leak into the kernel trace ("failed to legalize
    operation 'func.return'" on v5e). Kernel inputs/outputs are explicit f32/
    bf16, so disabling x64 inside the trace is semantics-preserving."""
    from .._compat import enable_x64
    return enable_x64(False)


def cost_estimate(flops, transcendentals=0, bytes_accessed=0):
    """``pl.CostEstimate`` for a ``pallas_call`` site, clamped to ints.

    Without it, XLA costs a custom call at zero FLOPs, so StepMetrics MFU
    (observability) under-reports every kernel-backed step. Values are
    ESTIMATES for attribution, not exact op counts — kernels pass the
    matmul/exp/traffic totals of the tile schedule they actually run
    (live tiles only for the varlen flat schedules). The AST lint
    tests/test_pallas_cost_lint.py keeps every kernel site honest."""
    from jax.experimental import pallas as pl
    return pl.CostEstimate(flops=max(int(flops), 0),
                           transcendentals=max(int(transcendentals), 0),
                           bytes_accessed=max(int(bytes_accessed), 0))


class _InterpretOverride:
    """Context manager that forces interpret mode for one block and
    restores the PREVIOUS override (not a hard-coded value) on exit —
    the restore discipline PTA007 enforces. Reentrant-safe: nesting
    saves/restores like a stack."""

    def __init__(self, value):
        self._value = value
        self._prev = None

    def __enter__(self):
        global _FORCE_INTERPRET
        self._prev = _FORCE_INTERPRET
        _FORCE_INTERPRET = self._value
        return self._value

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._prev
        return False


_UNSET = object()


def interpret_mode(value=_UNSET):
    """Dual-purpose.

    ``interpret_mode()`` (no args) — predicate: Pallas kernels must run
    interpreted off-TPU. The axon TPU plugin stays the default backend
    even when work is pinned to host CPU devices (tests, dryruns), so
    honor jax_default_device first.

    ``with interpret_mode(True):`` — scoped override of the predicate
    that saves and restores the previous override, replacing bare
    ``set_interpret(True)`` / ``set_interpret(False)`` pairs (the PR-10
    leak class: teardown that hard-codes ``False`` clobbers any outer
    override and poisons later tests in the same process)."""
    if value is not _UNSET:
        return _InterpretOverride(value)
    if _FORCE_INTERPRET is not None:
        return _FORCE_INTERPRET
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform == "cpu"
    return jax.default_backend() != "tpu"
