"""Shared kernel plumbing."""
from __future__ import annotations

import jax

_FORCE_INTERPRET = None


def set_interpret(value: bool | None):
    """Override interpret-mode detection (None = auto)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = value


def interpret_mode() -> bool:
    """Pallas kernels must run interpreted off-TPU. The axon TPU plugin stays
    the default backend even when work is pinned to host CPU devices (tests,
    dryruns), so honor jax_default_device first."""
    if _FORCE_INTERPRET is not None:
        return _FORCE_INTERPRET
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform == "cpu"
    return jax.default_backend() != "tpu"
