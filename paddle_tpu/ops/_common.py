"""Shared kernel plumbing."""
from __future__ import annotations

import jax

_FORCE_INTERPRET = None


def set_interpret(value: bool | None):
    """Override interpret-mode detection (None = auto)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = value


def mosaic_trace_ctx():
    """Trace Pallas kernels with x64 off: the package enables jax_enable_x64
    globally (Paddle dtype semantics), but Mosaic cannot legalize the 64-bit
    index/constant types that leak into the kernel trace ("failed to legalize
    operation 'func.return'" on v5e). Kernel inputs/outputs are explicit f32/
    bf16, so disabling x64 inside the trace is semantics-preserving."""
    from .._compat import enable_x64
    return enable_x64(False)


def interpret_mode() -> bool:
    """Pallas kernels must run interpreted off-TPU. The axon TPU plugin stays
    the default backend even when work is pinned to host CPU devices (tests,
    dryruns), so honor jax_default_device first."""
    if _FORCE_INTERPRET is not None:
        return _FORCE_INTERPRET
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform == "cpu"
    return jax.default_backend() != "tpu"
