"""Shared kernel plumbing."""
from __future__ import annotations

import jax

_FORCE_INTERPRET = None


def set_interpret(value: bool | None):
    """Override interpret-mode detection (None = auto)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = value


def mosaic_trace_ctx():
    """Trace Pallas kernels with x64 off: the package enables jax_enable_x64
    globally (Paddle dtype semantics), but Mosaic cannot legalize the 64-bit
    index/constant types that leak into the kernel trace ("failed to legalize
    operation 'func.return'" on v5e). Kernel inputs/outputs are explicit f32/
    bf16, so disabling x64 inside the trace is semantics-preserving."""
    from .._compat import enable_x64
    return enable_x64(False)


# Latest cost_estimate() values per named kernel site, recorded at TRACE
# time (cost_estimate() runs while jax traces the enclosing function, so
# after one lowering of a program the table holds the exact FLOPs/bytes
# each kernel site claimed for the shapes that program runs). Keys are the
# stable ``name=`` strings threaded through every pallas_call site;
# RooflineLedger (observability) joins this against the per-platform
# roofline tables for per-kernel compute/memory-bound attribution.
_KERNEL_COSTS: dict = {}


def cost_estimate(flops, transcendentals=0, bytes_accessed=0, name=None):
    """``pl.CostEstimate`` for a ``pallas_call`` site, clamped to ints.

    Without it, XLA costs a custom call at zero FLOPs, so StepMetrics MFU
    (observability) under-reports every kernel-backed step. Values are
    ESTIMATES for attribution, not exact op counts — kernels pass the
    matmul/exp/traffic totals of the tile schedule they actually run
    (live tiles only for the varlen flat schedules). The AST lint
    tests/test_pallas_cost_lint.py keeps every kernel site honest.

    ``name=`` is the site's stable kernel name: when given, the clamped
    values are recorded into the process-wide table behind
    :func:`kernel_cost_table` (keyed by that name, latest trace wins,
    ``calls`` counts how many traces hit the site)."""
    from jax.experimental import pallas as pl
    fl = max(int(flops), 0)
    tr = max(int(transcendentals), 0)
    ba = max(int(bytes_accessed), 0)
    if name is not None:
        rec = _KERNEL_COSTS.setdefault(
            name, {"flops": 0, "transcendentals": 0, "bytes_accessed": 0,
                   "calls": 0, "total_flops": 0, "total_transcendentals": 0,
                   "total_bytes_accessed": 0})
        rec["flops"] = fl
        rec["transcendentals"] = tr
        rec["bytes_accessed"] = ba
        rec["calls"] += 1
        # cumulative totals: a kernel called L times while one program
        # traces fires this L times, so the WINDOW DELTA of the totals
        # (snapshot_kernel_costs / kernel_costs_since) is that program's
        # exact per-step cost for the site — what RooflineLedger ingests
        rec["total_flops"] += fl
        rec["total_transcendentals"] += tr
        rec["total_bytes_accessed"] += ba
    return pl.CostEstimate(flops=fl, transcendentals=tr, bytes_accessed=ba)


def snapshot_kernel_costs() -> dict:
    """Opaque marker for :func:`kernel_costs_since` (per-name cumulative
    totals at this instant)."""
    return {name: (rec["calls"], rec["total_flops"],
                   rec["total_transcendentals"], rec["total_bytes_accessed"])
            for name, rec in _KERNEL_COSTS.items()}


def kernel_costs_since(snapshot: dict) -> dict:
    """Per-kernel cost accumulated since ``snapshot`` — trace one program
    between the two calls and this is its exact per-execution kernel cost,
    summed over every invocation (layers, chunks) of each named site."""
    out = {}
    for name, rec in _KERNEL_COSTS.items():
        c0, f0, t0, b0 = snapshot.get(name, (0, 0, 0, 0))
        calls = rec["calls"] - c0
        if calls <= 0:
            continue
        out[name] = {"calls": calls,
                     "flops": rec["total_flops"] - f0,
                     "transcendentals": rec["total_transcendentals"] - t0,
                     "bytes_accessed": rec["total_bytes_accessed"] - b0}
    return out


def reset_kernel_costs() -> None:
    """Clear the observed-cost table (test isolation; static sites stay)."""
    _KERNEL_COSTS.clear()


def _static_cost_sites():
    """AST enumeration of every ``pallas_call(..., cost_estimate=...)``
    site under ``ops/`` — the same sites the PTA003 lint floors — with the
    ``name=`` string literal pulled out of the cost-estimate call. Sites
    without a literal name key as ``<module>:<line>``."""
    import ast
    import os
    out = {}
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for fname in sorted(os.listdir(pkg_dir)):
        if not fname.endswith(".py") or fname.startswith("__"):
            continue
        with open(os.path.join(pkg_dir, fname), encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            ident = (fn.attr if isinstance(fn, ast.Attribute)
                     else fn.id if isinstance(fn, ast.Name) else None)
            if ident != "pallas_call":
                continue
            ce = next((kw.value for kw in node.keywords
                       if kw.arg == "cost_estimate"), None)
            if ce is None:
                continue
            name = None
            if isinstance(ce, ast.Call):
                for kw in ce.keywords:
                    if kw.arg == "name" and isinstance(kw.value,
                                                       ast.Constant):
                        name = kw.value.value
            key = name or f"{fname[:-3]}:{node.lineno}"
            out[key] = {"module": fname[:-3], "line": node.lineno,
                        "named": name is not None}
    return out


def kernel_cost_table() -> dict:
    """Every registered pallas_call cost site, keyed by stable kernel name.

    Merges the static AST enumeration (all sites, whether or not they have
    traced yet this process) with the runtime-observed values recorded by
    :func:`cost_estimate` ``name=``: each entry carries ``module``/``line``
    (where the site lives), ``named`` (has a stable name literal), and —
    once a program using the kernel has been traced — the latest
    ``flops``/``bytes_accessed``/``transcendentals`` plus a ``calls`` trace
    count (None/0 for sites not yet traced). PTA003 floors the site count;
    the unit test floors this table against the same constant."""
    table = _static_cost_sites()
    for name, rec in _KERNEL_COSTS.items():
        entry = table.setdefault(name, {"module": None, "line": None,
                                        "named": True})
        entry.update(rec)
    for entry in table.values():
        entry.setdefault("flops", None)
        entry.setdefault("bytes_accessed", None)
        entry.setdefault("transcendentals", None)
        entry.setdefault("calls", 0)
    return table


class _InterpretOverride:
    """Context manager that forces interpret mode for one block and
    restores the PREVIOUS override (not a hard-coded value) on exit —
    the restore discipline PTA007 enforces. Reentrant-safe: nesting
    saves/restores like a stack."""

    def __init__(self, value):
        self._value = value
        self._prev = None

    def __enter__(self):
        global _FORCE_INTERPRET
        self._prev = _FORCE_INTERPRET
        _FORCE_INTERPRET = self._value
        return self._value

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._prev
        return False


_UNSET = object()


def interpret_mode(value=_UNSET):
    """Dual-purpose.

    ``interpret_mode()`` (no args) — predicate: Pallas kernels must run
    interpreted off-TPU. The axon TPU plugin stays the default backend
    even when work is pinned to host CPU devices (tests, dryruns), so
    honor jax_default_device first.

    ``with interpret_mode(True):`` — scoped override of the predicate
    that saves and restores the previous override, replacing bare
    ``set_interpret(True)`` / ``set_interpret(False)`` pairs (the PR-10
    leak class: teardown that hard-codes ``False`` clobbers any outer
    override and poisons later tests in the same process)."""
    if value is not _UNSET:
        return _InterpretOverride(value)
    if _FORCE_INTERPRET is not None:
        return _FORCE_INTERPRET
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform == "cpu"
    return jax.default_backend() != "tpu"
