"""Pallas decode attention over the stacked KV-cache slabs.

Ref: paddle/fluid/operators/fused/fused_multi_transformer_op.cu (the
reference's decode kernel reads its cache in-place). TPU-native: the
kernel indexes the FULL stacked cache [L, B, KV*HD, T] directly via
scalar-prefetched (layer, pos) — no per-layer cache slice ever
materializes.

STATUS (r5, measured on v5e — why this is NOT the default decode path):
standalone, a 24-layer attention loop through this kernel beats the XLA
einsum path (423 vs 568 us at hd64 b8, floor 209); wired INTO the
decode scan it measured SLOWER end-to-end (2.9 vs 1.9 ms/step) across
three designs (per-batch grid, batch-in-block, batch-block-diagonal) —
the caches are in-place-updated scan carries, and a custom call reading
them appears to break XLA's in-place dynamic-update-slice (conservative
aliasing), re-copying cache state each layer. Owning the UPDATE too
(input_output_aliased cache in/outs) is the path to flipping this, at
the cost of write-back traffic for visited tiles. A second r5 finding
keeps the einsum path fast: RAGGED cache extents (257, not 256/384)
steer XLA to a copy-free slab layout — see models/llama.py
_prefill_for_generate. Until the aliased-update design lands, this
kernel serves callers whose caches are not loop carries.

Layout contract (matches models/llama.py's head_dim<128 "slab" cache):
  q_bd  [B, NH, KVD]    block-diagonal queries, PRE-SCALED by
                        scale*log2(e) (the kernel softmax runs in the
                        exp2 domain)
  cache [L, B, KVD, T]  k and v slabs, time in lanes
returns attn_full [B, NH, KVD] f32 (the caller gathers the diagonal
blocks back to heads).

The softmax uses the r5 fixed-base scheme (see flash_attention.py):
T-tile 0 anchors the exponent base — position 0 is always <= pos, so
every row has a live column there. Tiles wholly past `pos` are skipped
AND their DMA is elided (the index map clamps to the last live tile, so
Mosaic sees an unchanged block index and skips the copy).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import envs
from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx
from .flash_attention import softmax_mode

_LOG2E = 1.4426950408889634

# lanes per T tile: 512 bf16 lanes x KVD sublanes keeps each DMA big
# enough to stream at full HBM rate while bounding VMEM at long caches
DECODE_BLOCK_T = 512

# cap on the double-buffered k+v cache windows of one grid step
# (4 * block_t * per_lane_bytes must fit): the fixed 512-lane tile
# overflowed scoped VMEM for WIDE slabs — hd64 b8 (b=8, kvd=1024 bf16,
# 16 KB/lane) wants 32 MB of windows at 512 lanes vs the ~16 MB default
# window. 12 MB leaves headroom for q/scratch/out and compiler temps.
_DECODE_WINDOW_BUDGET = 12 * 1024 * 1024


def hd64_stack_mode():
    """PADDLE_TPU_DECODE_HD64_STACK=1 opts decode_attention_slab into the
    PAIR-STACKED hd64 kernel (two head_dim-64 heads per 128-lane MXU
    tile; see _kernel_pair). Default 0 keeps the batch-block-diagonal
    kernel — the r5-measured block choice stays the fallback."""
    return envs.get("PADDLE_TPU_DECODE_HD64_STACK")


def _env_block_t():
    """Validated PADDLE_TPU_DECODE_BLOCK_T override (None when unset).
    The r5 hd64_b8 rung sat at 1.36x of the bytes floor with the
    budget-fitted tile; the override lets the bench A/B-sweep tile sizes
    without editing the fitter (the winner then moves the default)."""
    return envs.get("PADDLE_TPU_DECODE_BLOCK_T")


def _fit_block_t(T, per_lane_bytes, n_windows=4):
    """Lanes per T tile: short caches take 128 (the pos-clamp skips
    dead-tile DMA at tile granularity, so finer tiles track the live
    prefix closely — a [KVD, 128] bf16 tile is still a full-rate DMA);
    long caches start at DECODE_BLOCK_T and HALVE until the
    double-buffered cache windows fit the VMEM budget, then halve again
    until the extent divides (cache extents are 128-multiples, so 128
    always divides).

    n_windows is the per-grid-step cache-window count the budget guards:
    4 for the read-only kernels (k+v, double-buffered); the fused
    attend+update kernel ALSO holds the two aliased k/v out windows, so
    it sizes against 6 — the r5 fitter under-counted those and could
    overcommit scoped VMEM on the update path at fat per-lane footprints.
    PADDLE_TPU_DECODE_BLOCK_T overrides the choice (still clipped to a
    divisor of T so the grid stays exact)."""
    forced = _env_block_t()
    if forced is not None:
        lanes = forced
        while T % lanes and lanes > 128:
            lanes //= 2
        return lanes
    lanes = 128 if T <= 2048 else DECODE_BLOCK_T
    while lanes > 128 and \
            n_windows * lanes * per_lane_bytes > _DECODE_WINDOW_BUDGET:
        lanes //= 2
    while T % lanes:
        lanes //= 2
    return lanes


def _kernel(lp_ref, q_ref, k_ref, v_ref, o_ref, qd_s, l_s, b_s, acc_s, *,
            block_t, n_t, nb, online=False):
    import numpy as np
    j = pl.program_id(0)
    pos = lp_ref[1]
    nh = q_ref.shape[1]
    kvd = q_ref.shape[2]
    start = j * np.int32(block_t)

    @pl.when(j == 0)
    def _build_qdiag():
        # batch-block-diagonal queries [B*NH, B*KVD], built ONCE per
        # layer call in VMEM: each T tile is then ONE MXU dot against
        # the batch-flattened [B*KVD, Tt] slab — per-batch [NH, KVD]
        # dots (M=16) ran at 1/8 MXU occupancy and a (B, n_t) grid
        # starved the pipeline; decode is bytes-bound, so the 8x padded
        # FLOPs are free while the DMA stream stays one big contiguous
        # read
        qd_s[...] = jnp.zeros(qd_s.shape, qd_s.dtype)
        for bi in range(nb):
            qd_s[bi * nh:(bi + 1) * nh,
                 bi * kvd:(bi + 1) * kvd] = q_ref[bi]

    def scores():
        k = k_ref[0].reshape(nb * kvd, block_t)
        s = jax.lax.dot_general(
            qd_s[...], k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [B*NH, Tt]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        v = v_ref[0].reshape(nb * kvd, block_t)
        return jax.lax.dot_general(
            p, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [B*NH, B*KVD]

    @pl.when(j == 0)
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p.astype(v_ref.dtype))

    @pl.when(jnp.logical_and(j > 0, start <= pos))
    def _more():
        s = scores()
        if online:
            # PADDLE_TPU_FLASH_SOFTMAX=online: running-max recurrence
            # instead of the tile-0 anchored base (see flash_attention)
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p.astype(v_ref.dtype))
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p.astype(v_ref.dtype))

    @pl.when(j == np.int32(n_t - 1))
    def _fin():
        big = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))
        for bi in range(nb):
            o_ref[bi] = big[bi * nh:(bi + 1) * nh,
                            bi * kvd:(bi + 1) * kvd]



def _kernel_pair(lp_ref, q_ref, k_ref, v_ref, o_ref, qd_s, l_s, b_s, acc_s,
                 *, block_t, n_t, nb, online=False):
    """PAIR-STACKED hd64 variant: grid (n_pairs, n_t). Each step handles
    ONE 128-sublane cache band = two head_dim-64 heads of every batch.
    The batch-block-diagonal query is [B*2, B*128] instead of
    [B*NH, B*KVD], cutting the padded MXU FLOPs by NH/2 (8x at nh=16)
    AND shrinking the per-lane window footprint by NH/2 — at hd64_b8
    the full 512-lane T tile fits the VMEM budget again where the wide
    slab had to drop to fragmented 128-lane DMAs (the 1.36x-of-floor r5
    gap). Cache bytes are unchanged: each band streams exactly once."""
    import numpy as np
    p_id = pl.program_id(0)
    j = pl.program_id(1)
    pos = lp_ref[1]
    two = q_ref.shape[1]          # = 2 heads per band
    band = q_ref.shape[2]         # = 128 lanes
    start = j * np.int32(block_t)

    @pl.when(j == 0)
    def _build_qdiag():
        # per-pair batch-block-diagonal queries [B*2, B*128], rebuilt at
        # each pair's first T tile (scratch persists across pairs)
        qd_s[...] = jnp.zeros(qd_s.shape, qd_s.dtype)
        for bi in range(nb):
            qd_s[bi * two:(bi + 1) * two,
                 bi * band:(bi + 1) * band] = q_ref[bi]

    def scores():
        k = k_ref[0].reshape(nb * band, block_t)
        s = jax.lax.dot_general(
            qd_s[...], k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [B*2, Tt]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        v = v_ref[0].reshape(nb * band, block_t)
        return jax.lax.dot_general(
            p, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [B*2, B*128]

    @pl.when(j == 0)
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p.astype(v_ref.dtype))

    @pl.when(jnp.logical_and(j > 0, start <= pos))
    def _more():
        s = scores()
        if online:
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p.astype(v_ref.dtype))
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p.astype(v_ref.dtype))

    @pl.when(j == np.int32(n_t - 1))
    def _fin():
        # out rows span the FULL kvd width: the pair only computes its
        # own 128-column band (the diagonal block the caller's eye
        # contraction keeps); off-band columns are explicit zeros — the
        # caller multiplies them by zero, so they must be finite, and
        # no other grid step ever presents these out rows
        big = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))
        kvd = o_ref.shape[2]
        for bi in range(nb):
            row = lax.dynamic_update_slice(
                jnp.zeros((two, kvd), jnp.float32),
                big[bi * two:(bi + 1) * two,
                    bi * band:(bi + 1) * band],
                (0, p_id * np.int32(band)))
            o_ref[bi] = row


def _tile_plan(T, layer, pos, per_lane_bytes, n_windows=4):
    """Shared tiling prologue for both slab kernels: (block_t, n_t, lp,
    live_map) or None for ragged (non-128-multiple) cache extents —
    ONE copy so the two entry points can never diverge in tiling.
    per_lane_bytes = b * kvd * cache-itemsize, the bytes one T lane
    contributes to a cache window (_fit_block_t sizes against it);
    n_windows is the kernel's cache-window count (4 read-only, 6 for
    the fused update with its aliased out windows)."""
    if T % 128:
        return None
    block_t = _fit_block_t(T, per_lane_bytes, n_windows)
    lp = jnp.stack([jnp.asarray(layer, jnp.int32),
                    jnp.asarray(pos, jnp.int32)])

    def live_map(j, lp_ref):
        # clamp to the last live tile: dead tiles re-present the same
        # block index and Mosaic skips their DMA
        jmax = lp_ref[1] // np.int32(block_t)
        return (lp_ref[0], 0, 0, jnp.minimum(j, jmax))

    return block_t, T // block_t, lp, live_map


def _kernel_update(lp_ref, q_ref, nk_ref, nv_ref, k_ref, v_ref,
                   o_ref, ko_ref, vo_ref, l_s, b_s, acc_s, *,
                   block_t, n_t, nb, online=False):
    import numpy as np
    j = pl.program_id(0)
    pos = lp_ref[1]
    nh = q_ref.shape[1]
    kvd = q_ref.shape[2]
    start = j * np.int32(block_t)
    pos_tile = pos // np.int32(block_t)
    col = pos - pos_tile * np.int32(block_t)
    lane = lax.broadcasted_iota(jnp.int32, (kvd, block_t), 1)

    def upd(tile_ref, new_ref, bi):
        # minor-dim insert must go through f32 (Mosaic: "Insertion of
        # minor dim that is not a no-op only supported for 32-bit
        # types"); runs on the pos tile ONLY
        new32 = new_ref[bi].astype(jnp.float32)[:, None]
        return jnp.where(lane == col, new32,
                         tile_ref[0, bi].astype(jnp.float32)) \
            .astype(tile_ref.dtype)

    @pl.when(j == pos_tile)
    def _write_cache():
        # the SAME out block index every grid step -> Mosaic writes the
        # tile back once; the new k/v column lands in-place (the out
        # refs alias the caches via input_output_aliases)
        for bi in range(nb):
            ko_ref[0, bi] = upd(k_ref, nk_ref, bi)
            vo_ref[0, bi] = upd(v_ref, nv_ref, bi)

    def chain(k_at, v_at, first):
        # one softmax step reading k/v via the given accessors; the
        # UPDATED pos tile is read back from the just-written out refs,
        # every other tile straight from the cache blocks — no blanket
        # fresh-column select pass (that select on every tile measured
        # ~0.11 ms/step at hd64 b8)
        rows = []
        for bi in range(nb):
            rows.append(jax.lax.dot_general(
                q_ref[bi], k_at(bi), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        s = jnp.concatenate(rows, axis=0)          # [B*NH, Tt]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(t <= pos, s, jnp.float32(-1e30))
        alpha = None
        if first:
            bvec = s.max(axis=-1, keepdims=True)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        elif online:
            # PADDLE_TPU_FLASH_SOFTMAX=online: running-max recurrence
            m_prev = b_s[:, :1]
            bvec = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - bvec)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        else:
            bvec = b_s[:, :1]
        p = jnp.exp2(s - bvec)
        psum = jnp.broadcast_to(p.sum(axis=-1, keepdims=True), l_s.shape)
        if first:
            l_s[...] = psum
        elif online:
            l_s[...] = l_s[...] * alpha + psum
        else:
            l_s[...] = l_s[...] + psum
        pb = p.astype(v_ref.dtype)
        for bi in range(nb):
            sl = slice(bi * nh, (bi + 1) * nh)
            d = jax.lax.dot_general(
                pb[sl], v_at(bi), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if first:
                acc_s[sl] = d
            elif online:
                acc_s[sl] = acc_s[sl] * alpha[sl] + d
            else:
                acc_s[sl] = acc_s[sl] + d

    def at(ref):
        return lambda bi: ref[0, bi]

    # 4-way branch: (first tile?) x (tile containing pos?) — the pos
    # tile reads the updated slabs back from the aliased out refs
    @pl.when(jnp.logical_and(j == 0, pos_tile == 0))
    def _first_updated():
        chain(at(ko_ref), at(vo_ref), True)

    @pl.when(jnp.logical_and(j == 0, pos_tile > 0))
    def _first_raw():
        chain(at(k_ref), at(v_ref), True)

    @pl.when(jnp.logical_and(j > 0,
                             jnp.logical_and(j == pos_tile,
                                             start <= pos)))
    def _more_updated():
        chain(at(ko_ref), at(vo_ref), False)

    @pl.when(jnp.logical_and(j > 0,
                             jnp.logical_and(j != pos_tile,
                                             start <= pos)))
    def _more_raw():
        chain(at(k_ref), at(v_ref), False)

    @pl.when(j == np.int32(n_t - 1))
    def _fin():
        big = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))
        for bi in range(nb):
            o_ref[bi] = big[bi * nh:(bi + 1) * nh]


def decode_attend_update_slab(q_bd, new_k, new_v, k_cache, v_cache,
                              layer, pos):
    """Fused cache-update + attention for one decode layer: writes the
    new k/v column IN PLACE (the caches alias through the custom call —
    input_output_aliases — so the scan carry stays a single buffer) and
    returns the attention over the live prefix including it.

    q_bd [B, NH, KVD] PRE-SCALED by scale*log2(e); new_k/new_v
    [B, KVD]; caches [L, B, KVD, T] with T a 128-multiple (returns None
    otherwise). Returns (attn [B, NH, KVD] f32, k_cache, v_cache)."""
    b, nh, kvd = q_bd.shape
    L, _, _, T = k_cache.shape
    it = jnp.dtype(k_cache.dtype).itemsize
    # 6 windows: double-buffered k+v in (4) + the aliased k/v outs (2)
    plan = _tile_plan(T, layer, pos, b * kvd * it, n_windows=6)
    if plan is None:
        return None
    block_t, n_t, lp, live_map = plan

    def pos_map(j, lp_ref):
        return (lp_ref[0], 0, 0, lp_ref[1] // np.int32(block_t))

    kernel = functools.partial(_kernel_update, block_t=block_t, n_t=n_t,
                               nb=b, online=softmax_mode() == "online")
    with _mosaic_ctx():
        out, kc, vc = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_t,),
                in_specs=[
                    pl.BlockSpec((b, nh, kvd), lambda j, lp_ref: (0, 0, 0)),
                    pl.BlockSpec((b, kvd), lambda j, lp_ref: (0, 0)),
                    pl.BlockSpec((b, kvd), lambda j, lp_ref: (0, 0)),
                    pl.BlockSpec((1, b, kvd, block_t), live_map),
                    pl.BlockSpec((1, b, kvd, block_t), live_map),
                ],
                out_specs=[
                    pl.BlockSpec((b, nh, kvd), lambda j, lp_ref: (0, 0, 0)),
                    pl.BlockSpec((1, b, kvd, block_t), pos_map),
                    pl.BlockSpec((1, b, kvd, block_t), pos_map),
                ],
                scratch_shapes=[
                    pltpu.VMEM((b * nh, 128), jnp.float32),
                    pltpu.VMEM((b * nh, 128), jnp.float32),
                    pltpu.VMEM((b * nh, kvd), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
                jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
            ],
            # operand indices count scalar-prefetch first: 0=lp, 1=q,
            # 2=new_k, 3=new_v, 4=k_cache, 5=v_cache
            input_output_aliases={4: 1, 5: 2},
            cost_estimate=_cost_estimate(
                flops=4 * b * nh * kvd * T,
                transcendentals=b * nh * T,
                bytes_accessed=2 * b * kvd * (T + block_t) * it,
                name="decode.attend_update_slab"),
            interpret=_interpret(),
        )(lp, q_bd, new_k, new_v, k_cache, v_cache)
    return out, kc, vc


def decode_attention_slab(q_bd, k_cache, v_cache, layer, pos):
    """q_bd [B, NH, KVD], PRE-SCALED by the caller with scale*log2(e)
    (the kernel softmax runs in the exp2 domain and applies no scaling
    itself); k_cache/v_cache [L, B, KVD, T]; layer/pos i32 scalars.
    Returns attn_full [B, NH, KVD] f32, or None when T isn't a
    128-multiple (caller falls back to its XLA path)."""
    b, nh, kvd = q_bd.shape
    L, _, _, T = k_cache.shape
    it = jnp.dtype(k_cache.dtype).itemsize
    if (hd64_stack_mode() and nh > 0 and kvd == nh * 64
            and nh % 2 == 0 and T % 128 == 0):
        return _decode_attention_slab_pair(q_bd, k_cache, v_cache,
                                           layer, pos)
    plan = _tile_plan(T, layer, pos, b * kvd * it)
    if plan is None:
        return None  # ragged cache: caller falls back to the XLA path
    block_t, n_t, lp, live_map = plan

    kernel = functools.partial(_kernel, block_t=block_t, n_t=n_t, nb=b,
                               online=softmax_mode() == "online")
    with _mosaic_ctx():
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_t,),
                in_specs=[
                    pl.BlockSpec((b, nh, kvd), lambda j, lp_ref: (0, 0, 0)),
                    pl.BlockSpec((1, b, kvd, block_t), live_map),
                    pl.BlockSpec((1, b, kvd, block_t), live_map),
                ],
                out_specs=pl.BlockSpec(
                    (b, nh, kvd), lambda j, lp_ref: (0, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((b * nh, b * kvd), q_bd.dtype),
                    pltpu.VMEM((b * nh, 128), jnp.float32),
                    pltpu.VMEM((b * nh, 128), jnp.float32),
                    pltpu.VMEM((b * nh, b * kvd), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
            # block-diagonal padded FLOPs are real MXU work (decode is
            # bytes-bound, so they are free in time but not in count)
            cost_estimate=_cost_estimate(
                flops=4 * b * b * nh * kvd * T,
                transcendentals=b * nh * T,
                bytes_accessed=2 * b * kvd * T * it,
                name="decode.attention_slab"),
            interpret=_interpret(),
        )(lp, q_bd, k_cache, v_cache)
    return out


def _decode_attention_slab_pair(q_bd, k_cache, v_cache, layer, pos):
    """hd64 pair-stacked slab attention (PADDLE_TPU_DECODE_HD64_STACK=1):
    same contract as decode_attention_slab, requiring head_dim == 64,
    even NH, and a 128-multiple cache extent (the caller checks).

    Grid (n_pairs, n_t), t minor: each pair's 128-sublane k/v band
    streams through all T tiles before the next pair starts; windows are
    [B, 128, block_t] so _fit_block_t sizes against B*128*itemsize per
    lane — NH/2 times thinner than the full slab, which is what lets
    hd64_b8 keep the full 512-lane DMA tile."""
    b, nh, kvd = q_bd.shape
    L, _, _, T = k_cache.shape
    it = jnp.dtype(k_cache.dtype).itemsize
    n_pairs = nh // 2
    block_t = _fit_block_t(T, b * 128 * it)
    n_t = T // block_t
    lp = jnp.stack([jnp.asarray(layer, jnp.int32),
                    jnp.asarray(pos, jnp.int32)])

    def live_map(p, j, lp_ref):
        # clamp dead T tiles to the last live one (DMA elided); the
        # sublane index picks the pair's 128-row cache band
        jmax = lp_ref[1] // np.int32(block_t)
        return (lp_ref[0], 0, p, jnp.minimum(j, jmax))

    def q_map(p, j, lp_ref):
        # q_bd is head-block-diagonal, so pair p's live columns are
        # exactly the p-th 128-lane band: block (0, p, p)
        return (0, p, p)

    def o_map(p, j, lp_ref):
        # full-width rows per pair (off-band columns zeroed in-kernel)
        return (0, p, 0)

    kernel = functools.partial(_kernel_pair, block_t=block_t, n_t=n_t,
                               nb=b, online=softmax_mode() == "online")
    with _mosaic_ctx():
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_pairs, n_t),
                in_specs=[
                    pl.BlockSpec((b, 2, 128), q_map),
                    pl.BlockSpec((1, b, 128, block_t), live_map),
                    pl.BlockSpec((1, b, 128, block_t), live_map),
                ],
                out_specs=pl.BlockSpec((b, 2, kvd), o_map),
                scratch_shapes=[
                    pltpu.VMEM((b * 2, b * 128), q_bd.dtype),
                    pltpu.VMEM((b * 2, 128), jnp.float32),
                    pltpu.VMEM((b * 2, 128), jnp.float32),
                    pltpu.VMEM((b * 2, b * 128), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
            # batch-diagonal padding is x B on a [2, 128] q block: NH/2
            # fewer padded FLOPs than the full-slab block-diagonal form
            cost_estimate=_cost_estimate(
                flops=8 * b * b * kvd * T,
                transcendentals=b * nh * T,
                bytes_accessed=2 * b * kvd * T * it,
                name="decode.slab_pair"),
            interpret=_interpret(),
        )(lp, q_bd, k_cache, v_cache)
    return out
