"""Flash attention as a Pallas TPU kernel.

Ref: paddle/phi/kernels/gpu/flash_attn_kernel.cu (the reference dlopens its
FlashAttention-2 fork). TPU-native rewrite, not a translation:

- forward: Pallas kernel, online-softmax over KV tiles held in VMEM, fp32
  accumulators, MXU matmuls with bf16 operands (preferred_element_type=f32).
  The [S, S] score matrix never exists in HBM. Emits per-row logsumexp.
- backward: two Pallas kernels using the saved logsumexp (standard FA2
  identities: dV = PᵀdO, dS = P∘(dP − rowsum(dO∘O)), dQ/dK from dS) —
  dK/dV over k-tiles x inner q loop, dQ over q-tiles x inner k loop, all
  tiles resident in VMEM. Ragged lengths via zero-pad + mask (see
  _flash_fwd / _flash_bwd_pallas docstrings).

Layout [B, S, H, D] (the reference's), GQA via KV-head repeat.
interpret=True under CPU so the same code runs in tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# v5e-tuned: 1024x1024 tiles keep the MXU fed (2.7x over 128x128 measured);
# min() clamps both to the actual sequence length for small inputs.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

# Scores are computed in the LOG2 domain: the callers fold scale·log2(e)
# into q, so the kernels' softmax uses exp2 directly. The VPU's exp is
# exp2 plus a multiply pass — folding the multiply into the [S, D] q
# prep deletes one full [BQ, BK] VPU pass per score tile. lse crosses
# the kernel boundary in the NATURAL-log domain (ring attention merges
# partial softmaxes with it).
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


from .. import envs
from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx
from .._compat import tpu_compiler_params as _tpu_compiler_params


def _fit_block(block, n):
    """Largest useful block <= `block` for length n, 128-aligned (Mosaic
    requires lane-tile-aligned vector loads; min(block, n) could yield e.g.
    300 which fails to legalize)."""
    return min(block, -(-n // 128) * 128)


def _attn_cost(bh, sp, skp, d, itemsize, causal, matmuls, extra_bytes=0,
               name=None):
    """pl.CostEstimate for a dense-attention kernel: `matmuls` [Sq, Sk]·D
    contractions over the (clamped-to-half under causal) score area, one
    exp per score, and the q/k/v/o-sized HBM traffic. ``name`` is the
    site's stable kernel name for ``kernel_cost_table`` attribution."""
    cf = 0.5 if causal else 1.0
    return _cost_estimate(
        flops=matmuls * 2 * bh * sp * skp * d * cf,
        transcendentals=bh * sp * skp * cf,
        bytes_accessed=bh * (2 * sp + 2 * skp) * d * itemsize + extra_bytes,
        name=name)


def _pad_rows(x, multiple):
    """Zero-pad axis 1 up to a multiple; returns (padded, original_len)."""
    n = x.shape[1]
    rem = (-n) % multiple
    if rem:
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, rem)
        x = jnp.pad(x, pad)
    return x, n


def _mask_scores(s, row0, col0, causal, row_limit=None, col_limit=None):
    """Trace-time-composed mask for one [R, C] score tile: causal
    (rows >= cols) and/or row/col validity limits (padding tails). Limits
    passed as None are elided from the trace entirely — a non-causal
    unpadded tile pays zero mask work. Shared by all six kernels (resident
    and streaming, fwd and bwd) so the boundary conditions cannot drift."""
    import numpy as np
    if not causal and row_limit is None and col_limit is None:
        return s
    r, c = s.shape
    ok = None
    cols = (col0 + lax.broadcasted_iota(jnp.int32, (r, c), 1)
            if (causal or col_limit is not None) else None)
    rows = (row0 + lax.broadcasted_iota(jnp.int32, (r, c), 0)
            if (causal or row_limit is not None) else None)
    if col_limit is not None:
        ok = cols < np.int32(col_limit)
    if row_limit is not None:
        t = rows < np.int32(row_limit)
        ok = t if ok is None else ok & t
    if causal:
        t = rows >= cols
        ok = t if ok is None else ok & t
    # strong f32 scalar: a weak Python literal re-canonicalizes to f64
    # when a consumer jit lowers under the package-global x64 (the MLIR
    # verifier rejects it — see the decode/paged strong-typing note)
    return jnp.where(ok, s, jnp.float32(-1e30))


def _tri_mask_const(block_q, block_k):
    """Additive lower-triangular mask tile ([BQ, BK] f32, 0 below/on the
    diagonal, -1e30 above). For self-attention with equal blocks, every
    causal-masked tile IS the diagonal tile, and its mask is identical
    across tiles — so a single precomputed tile turns the per-tile
    iota+compare+select (4-5 VPU passes, measured to cost causal D=64
    attention nearly all of its 2x FLOP advantage) into one add."""
    r = jnp.arange(block_q)[:, None]
    c = jnp.arange(block_k)[None, :]
    return jnp.where(r >= c, jnp.float32(0.0), jnp.float32(-1e30))


def _resident_loop_bounds(qi, bq_i, bk_i, seq_k, block_k, causal, mask_kv,
                          lo):
    """Shared masked/unmasked loop-split bounds for the resident forward
    kernels (ONE copy so the causal/kv-padding boundary conditions cannot
    drift between the online and fixed-base variants): returns (nblocks,
    first_masked) with first_masked clamped to at least ``lo`` (the fixed-
    base kernel consumes block 0 outside the loops)."""
    import numpy as np
    nblocks = np.int32(seq_k // block_k)
    if causal:
        # only blocks whose start <= last query position of this tile
        last_q = (qi + np.int32(1)) * bq_i - np.int32(1)
        nblocks = jnp.minimum(nblocks, last_q // bk_i + np.int32(1))
    # first block index that needs any masking: the causal diagonal
    # (rows >= cols can fail once j*bk > qi*bq) and/or the padded tail.
    first_masked = nblocks
    if causal:
        first_masked = jnp.minimum(first_masked, (qi * bq_i) // bk_i)
    if mask_kv:
        first_masked = jnp.minimum(first_masked, nblocks - np.int32(1))
    first_masked = jnp.maximum(first_masked, np.int32(lo))
    return nblocks, first_masked


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k, causal,
                seq_k, kv_len, use_tri=False):
    """seq_k is the PADDED key length (multiple of block_k); kv_len the true
    one — key positions >= kv_len are masked out so padding never attends.

    The softmax scale is FOLDED INTO Q by the caller (q arrives pre-scaled):
    the per-tile `s * scale` was a full [BQ, BK] f32 VPU pass per tile, a
    measurable share of a kernel that is softmax-(VPU-)bound.

    The KV loop is split into an unmasked region (blocks fully below the
    causal diagonal and clear of padding) and a masked tail: the mask iota/
    where work is VPU-side and the kernel is softmax-(VPU-)bound at small D,
    so skipping it on interior blocks is a real win. With use_tri (equal
    blocks, no kv padding) the masked region is exactly the diagonal tile
    and applies the precomputed additive mask — see _tri_mask_const."""
    import numpy as np
    if use_tri:
        tri_ref, o_ref, lse_ref = rest
    else:
        (o_ref, lse_ref), tri_ref = rest, None
    bk_i = np.int32(block_k)  # i32 casts are belt-and-braces; the trace runs
    # under mosaic_trace_ctx (x64 disabled) — see _common.mosaic_trace_ctx
    qi = pl.program_id(1)
    # keep q/k in their storage dtype (bf16) for the dot — the MXU runs
    # bf16 x bf16 -> f32 at full rate, while f32 x f32 is ~8x slower; the
    # fp32 scale is applied to the f32 accumulator after the matmul.
    q = q_ref[0]                                      # [BQ, D]
    bq, d = q.shape
    bq_i = np.int32(bq)
    m = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    mask_kv = kv_len != seq_k
    nblocks, first_masked = _resident_loop_bounds(
        qi, bq_i, bk_i, seq_k, block_k, causal, mask_kv, 0)

    def body(j, carry, *, masked):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * bk_i, block_k), :]
        v = v_ref[0, pl.ds(j * bk_i, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if masked:
            if use_tri:
                s = s + tri_ref[...]
            else:
                s = _mask_scores(s, qi * bq_i, j * bk_i, causal,
                                 col_limit=kv_len if mask_kv else None)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp2(m - m_new)
        p = jnp.exp2(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal or mask_kv:
        carry = lax.fori_loop(np.int32(0), first_masked,
                              functools.partial(body, masked=False),
                              (m, l, acc))
        m, l, acc = lax.fori_loop(first_masked, nblocks,
                                  functools.partial(body, masked=True), carry)
    else:
        m, l, acc = lax.fori_loop(np.int32(0), nblocks,
                                  functools.partial(body, masked=False),
                                  (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # 2-D store ([1, BQ]); Mosaic fails to legalize 1-D vector stores.
    lse_ref[0] = ((m + jnp.log2(jnp.maximum(l, 1e-30))) * _LN2).T


def _fwd_kernel_fixed_base(q_ref, k_ref, v_ref, *rest, block_k, causal,
                           seq_k, kv_len, use_tri=False):
    """FIXED-BASE variant of _fwd_kernel (r5): block 0's row max anchors
    the exponent base for the whole row, so later blocks' p never wait
    on the current block's reduction and acc never rescales — the
    online-max data path (not exp2/sum) was measured as the entire
    0.633-vs-0.821 eff gap on the streaming kernel. Numerics: later
    blocks' p = exp2(s - base) may exceed 1; f32 holds 2^127 of
    headroom, so results are exact unless a row's true max exceeds
    block 0's by >~100 log2 units (no realistic attention; the failure
    is a LOUD inf/nan, never silent). Selected only when the extra
    s0/p0 live ranges fit scoped VMEM (see _flash_fwd)."""
    import numpy as np
    if use_tri:
        tri_ref, o_ref, lse_ref = rest
    else:
        (o_ref, lse_ref), tri_ref = rest, None
    bk_i = np.int32(block_k)
    qi = pl.program_id(1)
    q = q_ref[0]                                      # [BQ, D]
    bq, d = q.shape
    bq_i = np.int32(bq)

    mask_kv = kv_len != seq_k
    nblocks, first_masked = _resident_loop_bounds(
        qi, bq_i, bk_i, seq_k, block_k, causal, mask_kv, 1)

    # block 0 anchors the base; masked unconditionally (no-op for
    # qi > 0 causal rows, keeps the base finite when block 0 IS the
    # diagonal or kv_len < block_k). Block 0 always has a live column.
    k0 = k_ref[0, pl.ds(0, block_k), :]
    v0 = v_ref[0, pl.ds(0, block_k), :]
    s0 = jnp.dot(q, k0.T, preferred_element_type=jnp.float32)
    s0 = _mask_scores(s0, qi * bq_i, 0, causal,
                      col_limit=kv_len if mask_kv else None)
    base = s0.max(axis=-1, keepdims=True)
    p0 = jnp.exp2(s0 - base)
    l = p0.sum(axis=-1, keepdims=True)
    acc = jnp.dot(p0.astype(v0.dtype), v0,
                  preferred_element_type=jnp.float32)

    def body(j, carry, *, masked):
        l, acc = carry
        k = k_ref[0, pl.ds(j * bk_i, block_k), :]
        v = v_ref[0, pl.ds(j * bk_i, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if masked:
            if use_tri:
                s = s + tri_ref[...]
            else:
                s = _mask_scores(s, qi * bq_i, j * bk_i, causal,
                                 col_limit=kv_len if mask_kv else None)
        p = jnp.exp2(s - base)
        l_new = l + p.sum(axis=-1, keepdims=True)
        acc_new = acc + jnp.dot(p.astype(v.dtype), v,
                                preferred_element_type=jnp.float32)
        return l_new, acc_new

    if causal or mask_kv:
        carry = lax.fori_loop(np.int32(1), first_masked,
                              functools.partial(body, masked=False),
                              (l, acc))
        l, acc = lax.fori_loop(first_masked, nblocks,
                               functools.partial(body, masked=True), carry)
    else:
        l, acc = lax.fori_loop(np.int32(1), nblocks,
                               functools.partial(body, masked=False),
                               (l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = ((base + jnp.log2(jnp.maximum(l, 1e-30))) * _LN2).T


# Escape hatch (ADVICE r5): the fixed-base scheme anchors every row's
# exponent base on block/tile 0's max, which overflows (LOUD inf/nan, never
# silent) if a later block's true row max exceeds it by >~100 log2 units.
# Callers with such heavy-tailed logits set PADDLE_TPU_FLASH_SOFTMAX=online
# to force the unconditionally-stable online-softmax recurrence in every
# kernel that has a fixed-base variant (resident forward, streaming
# forward, decode slabs). Read per call so tests can flip it via
# monkeypatched env.
ENV_FLASH_SOFTMAX = "PADDLE_TPU_FLASH_SOFTMAX"


def softmax_mode() -> str:
    """'auto' (fixed-base wherever its VMEM budget fits) or 'online'."""
    return envs.get(ENV_FLASH_SOFTMAX)


# scoped-VMEM budget for selecting the fixed-base resident kernel: its
# extra s0/p0 live ranges cost ~2 more [BQ, BK] f32 buffers than the
# online kernel (measured: flagship 1024^2 blocks hit 16.02M > 16M)
_FB_RESIDENT_BUDGET = 13 * 1024 * 1024


def _fb_resident_fits(skp, d, bq, bk, itemsize):
    kv = 2 * skp * d * itemsize * 2          # k+v, double-buffered
    sp = 4 * bq * bk * 4                     # s0/p0 + loop s/p, f32
    io = 2 * bq * d * itemsize * 2           # q + o
    tri = bq * bk * 4
    return kv + sp + io + tri < _FB_RESIDENT_BUDGET


def _resident_kernel_choice(skp, d, bq, bk, itemsize):
    """The resident forward kernel _flash_fwd will run: fixed-base when the
    escape hatch is off and its scoped-VMEM stack fits, else online."""
    if softmax_mode() == "online":
        return _fwd_kernel
    return (_fwd_kernel_fixed_base
            if _fb_resident_fits(skp, d, bq, bk, itemsize) else _fwd_kernel)


# whole-KV-in-VMEM ceiling: above this the forward streams KV tiles through
# a third grid dimension instead. Empirical (v5e, 16MB scoped vmem): the
# resident kernel's scoped stack is ~2x(K+V) (double buffering) + ~1.3MB, so
# K+V beyond ~3MB (S=8192 at D=128 bf16 measured 17.33M > 16M) must stream.
STREAM_KV_BYTES = 3 * 2 ** 20


def _fwd_kernel_stream(q_ref, k_ref, v_ref, *rest, block_k, causal, kv_len,
                       seq_k, n_k, use_tri=False, online=False):
    """Streaming variant: grid (BH, n_q, n_k); one KV tile per step, online
    stats in VMEM scratch persisted across the innermost (sequential) k
    steps. Removes the whole-KV VMEM residency ceiling (S beyond ~12k at
    D=128). Perf notes (profiled on-device at S=16k, D=128, 1024x1024
    tiles — wall-clock over the tunnel is dispatch-dominated and useless;
    see bench.py long_seq):

    - seq_k is the PADDED key length, a Python int: when kv_len == seq_k
      (no padding) the tail compare is elided at trace time, and a
      non-causal unpadded call runs with no mask work at all.
    - use_tri (equal blocks, no kv padding): the only tiles the causal
      mask BITES are the ki == qi diagonal tiles, so the iota+compare+
      select (multiple VPU passes on EVERY live tile of a VPU-bound
      kernel) collapses to one fused multiply-add of a precomputed
      additive tri tile by a per-step scalar flag. An earlier lax.cond
      boundary/interior split measured 0.34 eff vs 0.55 for the plain
      where() — Mosaic branches defeat the pipeline; the scalar-flag
      multiply keeps the body branch-free.
    - fully-above-diagonal causal tiles are never DMA'd: the caller clamps
      the k/v BlockSpec index to the last needed tile, so Mosaic sees an
      unchanged block index and skips the copy (see _kv_clamp_map;
      profiled 0.55 -> 0.60 eff).
    - finalize at a dynamic last-needed index measured slightly SLOWER
      than writing at n_k - 1; keep the static finalize."""
    import numpy as np
    if use_tri:
        tri_ref, o_ref, lse_ref, m_s, l_s, acc_s = rest
    else:
        (o_ref, lse_ref, m_s, l_s, acc_s), tri_ref = rest, None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq = q_ref.shape[1]
    bq_i, bk_i = np.int32(bq), np.int32(block_k)

    start = ki * bk_i
    mask_kv = kv_len != seq_k
    needed = start < np.int32(kv_len)
    if causal:
        last_q = (qi + np.int32(1)) * bq_i - np.int32(1)
        needed = jnp.logical_and(needed, start <= last_q)

    # FIXED-BASE softmax (r5, see _fwd_kernel): tile 0's row max anchors
    # the exponent base for all later tiles, so p never waits on the
    # current tile's reduction and acc never rescales (measured 0.633 ->
    # 0.82 eff at S=32k; the exp2+sum are free, the online-max data
    # path was the whole gap). Tile 0 always has a live column. With
    # online=True (PADDLE_TPU_FLASH_SOFTMAX=online) m_s instead carries
    # the running row max and l/acc rescale each tile — the
    # unconditionally-stable recurrence for heavy-tailed logits.
    @pl.when(ki == 0)
    def _first():
        q = q_ref[0]
        s = jnp.dot(q, k_ref[0].T, preferred_element_type=jnp.float32)
        # mask unconditionally: no-op for qi > 0 causal rows, keeps the
        # base finite on the qi == 0 diagonal / short-kv tiles
        s = _mask_scores(s, qi * bq_i, 0, causal,
                         col_limit=kv_len if mask_kv else None)
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        m_s[...] = jnp.broadcast_to(base, m_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                             preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(needed, ki > 0))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if use_tri:
            # equal blocks: diagonal tile iff ki == qi (bq == bk)
            diag = (ki == qi).astype(jnp.float32)
            s = s + tri_ref[...] * diag
        else:
            s = _mask_scores(s, qi * bq_i, start, causal,
                             col_limit=kv_len if mask_kv else None)
        if online:
            m_prev = m_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        else:
            base = m_s[:, :1]
            p = jnp.exp2(s - base)
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(ki == np.int32(n_k - 1))
    def _finalize():
        m = m_s[:, :1]
        l = l_s[:, :1]
        o_ref[0] = (acc_s[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = ((m + jnp.log2(jnp.maximum(l, 1e-30))) * _LN2).T


def _kv_clamp_map(block_q, block_k, causal):
    """k/v BlockSpec index map for (bh, n_q, n_k) streaming grids: under
    causal, clamp the k tile index to the last tile this q tile attends to,
    so fully-above-diagonal steps present an UNCHANGED block index and
    Mosaic's pipeline skips their DMA entirely (the compute is already
    gated in-kernel). ~2x bandwidth saved on causal streams."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def _map(b, i, j):
        jmax = ((i + 1) * block_q - 1) // block_k
        return (b, jnp.minimum(j, jmax), 0)

    return _map


def _q_clamp_map(block_q, block_k, causal, stat=False):
    """q-side (and lse/delta when stat=True) BlockSpec index map for
    (bh, n_k, n_q) streaming dK/dV grids: under causal, clamp the q tile
    index UP to the first tile at/below the diagonal for this k tile, so
    fully-above-diagonal steps re-present the same block index and skip
    their DMA (dual of _kv_clamp_map)."""
    if not causal:
        return ((lambda b, j, i: (b, 0, i)) if stat
                else (lambda b, j, i: (b, i, 0)))

    def _map(b, j, i):
        imin = (j * block_k) // block_q
        i = jnp.maximum(i, imin)
        return (b, 0, i) if stat else (b, i, 0)

    return _map


def _flash_fwd_stream(qp, kp, vp, causal, block_q, block_k, sk,
                      out_dtype):
    bh, sp, d = qp.shape
    skp = kp.shape[1]
    n_k = skp // block_k
    use_tri = causal and sk == skp and block_q == block_k
    kernel = functools.partial(_fwd_kernel_stream, block_k=block_k,
                               causal=causal, kv_len=sk,
                               seq_k=skp, n_k=n_k, use_tri=use_tri,
                               online=softmax_mode() == "online")
    kv_map = _kv_clamp_map(block_q, block_k, causal)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_map),
        pl.BlockSpec((1, block_k, d), kv_map),
    ]
    args = [qp, kp, vp]
    if use_tri:
        in_specs.append(pl.BlockSpec((block_q, block_k),
                                     lambda b, i, j: (0, 0)))
        args.append(_tri_mask_const(block_q, block_k))
    with _mosaic_ctx():
        return pl.pallas_call(
            kernel,
            grid=(bh, sp // block_q, n_k),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, out_dtype),
                jax.ShapeDtypeStruct((bh, 1, sp), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            cost_estimate=_attn_cost(bh, sp, skp, d, qp.dtype.itemsize,
                                     causal, matmuls=2,
                                     name="flash.fwd_stream"),
            interpret=_interpret(),
        )(*args)


def _small_d_blocks(d, block_q, block_k):
    """At D<=64 the kernel is at the MXU's half-rate (K=64) ceiling and
    512x512 tiles measure ~10% faster than 1024x1024 (smaller tiles keep
    the VPU softmax overlapped); only shrink caller DEFAULTS, never an
    explicit smaller choice."""
    if d <= 64:
        return min(block_q, 512), min(block_k, 512)
    return block_q, block_k


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q, k, v: [BH, S, D] (same head count). Returns (o, lse).

    Ragged sequence lengths are handled by zero-padding to block multiples
    (manual `pl.ds` slices clamp out-of-bounds starts, which would silently
    re-read earlier rows) and masking padded key positions."""
    bh, s, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _small_d_blocks(d, block_q, block_k)
    block_q = _fit_block(block_q, s)
    block_k = _fit_block(block_k, sk)
    # fold the softmax scale AND the exp->exp2 conversion into q once
    # ([S, D] elementwise) instead of per score tile ([BQ, BK] x n_tiles);
    # scale=None marks q as ALREADY pre-scaled (the custom-vjp path saves
    # q̃ in its residuals so the backward reuses it)
    if scale is not None:
        q = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qp, _ = _pad_rows(q, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    sp, skp = qp.shape[1], kp.shape[1]
    if 2 * skp * d * k.dtype.itemsize > STREAM_KV_BYTES:
        o, lse = _flash_fwd_stream(qp, kp, vp, causal, block_q,
                                   block_k, sk, q.dtype)
        return o[:, :s], lse.reshape(bh, sp)[:, :s]
    grid = (bh, sp // block_q)
    use_tri = causal and sk == skp and block_q == block_k
    kern_fn = _resident_kernel_choice(skp, d, block_q, block_k,
                                      q.dtype.itemsize)
    kernel = functools.partial(kern_fn, block_k=block_k, causal=causal,
                               seq_k=skp, kv_len=sk,
                               use_tri=use_tri)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, skp, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, skp, d), lambda b, i: (b, 0, 0)),
    ]
    args = [qp, kp, vp]
    if use_tri:
        in_specs.append(pl.BlockSpec((block_q, block_k), lambda b, i: (0, 0)))
        args.append(_tri_mask_const(block_q, block_k))
    with _mosaic_ctx():
        o, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, 1, sp), jnp.float32),
            ],
            cost_estimate=_attn_cost(bh, sp, skp, d, q.dtype.itemsize,
                                     causal, matmuls=2,
                                     name="flash.fwd"),
            interpret=_interpret(),
        )(*args)
    return o[:, :s], lse.reshape(bh, sp)[:, :s]


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, block_q, causal, seq_q, q_len,
                    use_tri=False):
    """dK/dV: grid (bh, k_blocks); inner loop over q tiles >= the diagonal.

    q arrives PRE-SCALED (q̃ = scale·q, folded by the caller): with
    ds̃ = P∘(dP−δ) (no scale), dK = scale·ds̃ᵀ·q = ds̃ᵀ·q̃ exactly — both
    per-tile scale multiplies vanish. dV = PᵀdO is scale-free anyway.

    seq_q is the padded query length (block_q multiple); q rows >= q_len are
    zero padding and get masked so exp(0 - lse_pad) can't contribute.
    use_tri: see _tri_mask_const."""
    import numpy as np
    if use_tri:
        tri_ref, dk_ref, dv_ref = rest
    else:
        (dk_ref, dv_ref), tri_ref = rest, None
    ki = pl.program_id(1)
    k = k_ref[0]                                  # [BK, D] storage dtype
    v = v_ref[0]
    bk, d = k.shape
    bq_i = np.int32(block_q)
    bk_i = np.int32(bk)
    acc_dk = jnp.zeros((bk, d), jnp.float32)
    acc_dv = jnp.zeros((bk, d), jnp.float32)
    mask_q = q_len != seq_q
    nq = np.int32(seq_q // block_q)
    start = (ki * bk_i) // bq_i if causal else np.int32(0)

    def body(i, carry, *, masked):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * bq_i, block_q), :]        # [BQ, D]
        dob = do_ref[0, pl.ds(i * bq_i, block_q), :]
        lseb = lse_ref[0, 0, pl.ds(i * bq_i, block_q)]    # [BQ] f32
        deltab = delta_ref[0, 0, pl.ds(i * bq_i, block_q)]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32)
        if masked:
            if use_tri:
                s = s + tri_ref[...]
            else:
                s = _mask_scores(s, i * bq_i, ki * bk_i, causal,
                                 row_limit=q_len if mask_q else None)
        p = jnp.exp2(s - lseb[:, None])                    # [BQ, BK] f32
        p_lo = p.astype(v.dtype)
        dv = dv + jnp.dot(p_lo.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None])).astype(v.dtype)
        dk = dk + jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)
        return dk, dv

    if causal or mask_q:
        # q tiles straddling the causal diagonal need the mask; tiles fully
        # below it don't; the last tile needs it again when q is padded.
        if causal:
            diag_end = -((ki * bk_i + bk_i) // -bq_i)     # ceil-div
            diag_end = jnp.clip(diag_end, start, nq)
        else:
            diag_end = start
        un_end = jnp.maximum(diag_end, nq - np.int32(1)) if mask_q else nq
        carry = lax.fori_loop(start, diag_end,
                              functools.partial(body, masked=True),
                              (acc_dk, acc_dv))
        carry = lax.fori_loop(diag_end, un_end,
                              functools.partial(body, masked=False), carry)
        acc_dk, acc_dv = lax.fori_loop(un_end, nq,
                                       functools.partial(body, masked=True),
                                       carry)
    else:
        acc_dk, acc_dv = lax.fori_loop(start, nq,
                                       functools.partial(body, masked=False),
                                       (acc_dk, acc_dv))
    # q̃ carries an extra log2e (log2-domain scores); undo it on dK only
    dk_ref[0] = (acc_dk * _LN2).astype(dk_ref.dtype)
    dv_ref[0] = acc_dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, block_k, causal, scale, seq_k, kv_len,
                   use_tri=False):
    """dQ: grid (bh, q_blocks); inner loop over k tiles <= the diagonal.
    q arrives pre-scaled (see _bwd_dkv_kernel): dQ = scale·(ds̃·K), with
    the single scale multiply applied to the [BQ, D] accumulator at
    finalize instead of per [BQ, BK] score tile.
    seq_k is padded; key positions >= kv_len are masked out.
    use_tri: see _tri_mask_const."""
    import numpy as np
    if use_tri:
        tri_ref, dq_ref = rest
    else:
        (dq_ref,), tri_ref = rest, None
    qi = pl.program_id(1)
    qb = q_ref[0]                                 # [BQ, D]
    dob = do_ref[0]
    bq, d = qb.shape
    bq_i = np.int32(bq)
    bk_i = np.int32(block_k)
    lseb = lse_ref[0, 0, :]                       # [BQ]
    deltab = delta_ref[0, 0, :]
    acc = jnp.zeros((bq, d), jnp.float32)
    mask_kv = kv_len != seq_k
    nblocks = np.int32(seq_k // block_k)
    if causal:
        last_q = (qi + np.int32(1)) * bq_i - np.int32(1)
        nblocks = jnp.minimum(nblocks, last_q // bk_i + np.int32(1))

    def body(j, acc, *, masked):
        kb = k_ref[0, pl.ds(j * bk_i, block_k), :]
        vb = v_ref[0, pl.ds(j * bk_i, block_k), :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
        if masked:
            if use_tri:
                s = s + tri_ref[...]
            else:
                s = _mask_scores(s, qi * bq_i, j * bk_i, causal,
                                 col_limit=kv_len if mask_kv else None)
        p = jnp.exp2(s - lseb[:, None])
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None])).astype(kb.dtype)
        return acc + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    if causal or mask_kv:
        first_masked = nblocks
        if causal:
            first_masked = jnp.minimum(first_masked, (qi * bq_i) // bk_i)
        if mask_kv:
            first_masked = jnp.minimum(first_masked, nblocks - np.int32(1))
        first_masked = jnp.maximum(first_masked, np.int32(0))
        acc = lax.fori_loop(np.int32(0), first_masked,
                            functools.partial(body, masked=False), acc)
        acc = lax.fori_loop(first_masked, nblocks,
                            functools.partial(body, masked=True), acc)
    else:
        acc = lax.fori_loop(np.int32(0), nblocks,
                            functools.partial(body, masked=False), acc)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                      q_prescaled=False):
    """Pallas FA2 backward: tiles stay in VMEM (the jnp formulation streamed
    [S, BK] intermediates through HBM — bandwidth-bound).

    Ragged lengths: inputs are zero-padded to block multiples with padded
    positions masked in the kernels (see _flash_fwd). Known limit: each
    kernel stages the full opposing sequence (q/do resp. k/v) in VMEM per
    grid step, so VMEM bounds the practical single-shard sequence length
    (~16k at d=64 on v5e); longer contexts belong on the ring-attention
    path which shards the sequence."""
    bh, s, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _small_d_blocks(d, block_q, block_k)
    block_q = _fit_block(block_q, s)
    block_k = _fit_block(block_k, sk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp, _ = _pad_rows(q, block_q)
    dop, _ = _pad_rows(do, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    sp, skp = qp.shape[1], kp.shape[1]
    lse3, _ = _pad_rows(lse.reshape(bh, s, 1), block_q)
    delta3, _ = _pad_rows(delta.reshape(bh, s, 1), block_q)
    lse3 = lse3.reshape(bh, 1, sp)
    delta3 = delta3.reshape(bh, 1, sp)

    dq, dk, dv = _bwd_pallas_calls(qp, kp, vp, dop, lse3, delta3, causal,
                                   scale, block_q, block_k, q_len=s,
                                   kv_len=sk, q_prescaled=q_prescaled)
    return dq[:, :s], dk[:, :sk], dv[:, :sk]




def _bwd_fused_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             dqp_ref, dk_ref, dv_ref, dk_s, dv_s, dq_s, *,
                             block_q, block_k, causal, q_len, seq_q,
                             n_q, n_sub, col_tile0=0):
    """Fused streaming backward: ONE pass per (k-tile, q-tile) computes all
    five FA2 matmuls (S=QKᵀ, dP=dO·Vᵀ, dV=PᵀdO, dQ+=dS·K, dK+=dSᵀQ).

    The previous split (dK/dV kernel + dQ kernel) recomputed S and dP in
    both kernels — 7 matmuls per tile pair, capping backward efficiency
    at 5/7 of forward (measured r3: bwd 0.42-0.43 vs fwd 0.60-0.64).

    Grid (bh, n_kdma, n_q, n_sub): the k/v DMA block (bkdma = n_sub
    compute tiles) amortizes one fetch over the whole inner sweep, while
    each compute sub-tile is its own grid step so causal liveness gates
    at COMPUTE granularity (an unrolled in-kernel sub loop wasted a full
    dead sub-tile on every diagonal DMA block, ~5% at S=32k, and its n_sub
    live intermediates blew VMEM past bkdma=2048).

    dK/dV accumulate in VMEM scratch (slot = sub index) across the inner
    (q, sub) sweep. dQ accumulates over the OUTER kv dim, which scratch
    cannot span — each (kv-block, q-tile) window accumulates sub
    contributions in f32 scratch and flushes once, at the last LIVE sub,
    to a per-kv-block partial (grid-indexed output, the splash-attention
    pattern); the caller reduces partials with a liveness-masked sum (dead
    (j, i) slots are never written — their q-side index maps clamp to the
    first live tile, so they cost neither DMA nor flush)."""
    import numpy as np
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    si = pl.program_id(3)
    bq_i, bk_i = np.int32(block_q), np.int32(block_k)
    ns_i = np.int32(n_sub)
    # ABSOLUTE compute-tile column index (col_tile0 = this kv chunk's
    # offset when the caller chunks long sequences)
    ct = np.int32(col_tile0) + ki * ns_i + si
    mask_q = q_len != seq_q

    @pl.when(jnp.logical_and(qi == 0, si == 0))
    def _init():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    if causal:
        needed = (qi + 1) * bq_i > ct * bk_i
        # last live sub of this (kv-block, q-tile) window: flush dq there
        si_last = jnp.clip(
            ((qi + 1) * bq_i - 1) // bk_i - np.int32(col_tile0)
            - ki * ns_i, np.int32(0), ns_i - 1)
    else:
        needed = si == si
        si_last = ns_i - 1

    @pl.when(needed)
    def _compute():
        qb = q_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0, 0, :]
        deltab = delta_ref[0, 0, :]
        k = k_ref[0, pl.ds(si * bk_i, block_k), :]
        v = v_ref[0, pl.ds(si * bk_i, block_k), :]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32)
        # iota mask, not a precomputed tri tile: the bwd kernel is
        # MXU-bound (VPU has slack) and the 4MB tri constant pushed the
        # bkdma=4096 configuration over the 16M scoped-VMEM limit
        s = _mask_scores(s, qi * bq_i, ct * bk_i, causal,
                         row_limit=q_len if mask_q else None)
        p = jnp.exp2(s - lseb[:, None])
        p_lo = p.astype(v.dtype)
        sl = pl.ds(si * bk_i, block_k)
        dv_s[sl, :] = dv_s[sl, :] + jnp.dot(
            p_lo.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None])).astype(v.dtype)
        dk_s[sl, :] = dk_s[sl, :] + jnp.dot(
            ds.T, qb, preferred_element_type=jnp.float32)
        contrib = jnp.dot(ds, k, preferred_element_type=jnp.float32)
        acc = jnp.where(si == 0, contrib, dq_s[...] + contrib)
        dq_s[...] = acc

        @pl.when(si == si_last)
        def _flush_dq():
            dqp_ref[0, 0] = acc.astype(dqp_ref.dtype)

    @pl.when(jnp.logical_and(qi == np.int32(n_q - 1), si == ns_i - 1))
    def _finalize():
        # q̃ carries an extra log2e (log2-domain scores); undo it on dK
        dk_ref[0] = (dk_s[...] * _LN2).astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


# k/v DMA block of the fused backward = this multiple of the compute tile
# (bounded by VMEM: dk/dv scratch 2·bkdma·D f32 + double-buffered k/v
# DMA windows; one sub-tile of matmul intermediates regardless of mult)
_BWD_KV_DMA_MULT = 8


# upper bound on dq-partial copies per pallas_call: the partial buffer is
# n_k x full-dq, which would grow quadratically with S — beyond this many
# kv DMA blocks the kv dimension is chunked at the XLA level instead
# (fixed partial footprint per chunk, dq accumulated across chunks)
_BWD_MAX_DQ_PARTIALS = 16


def _bwd_fused_stream_call(qp, kp, vp, dop, lse3, delta3, causal, scale,
                           block_q, block_k, q_len):
    """Fused backward: dq reduced from per-kv-DMA-block partials by a
    liveness-masked XLA sum, kv dimension chunked so the partial buffer
    stays bounded (<= _BWD_MAX_DQ_PARTIALS full-dq copies per chunk
    regardless of S)."""
    bh, sp, d = qp.shape
    skp = kp.shape[1]
    bkdma = block_k * _BWD_KV_DMA_MULT
    while skp % bkdma:
        bkdma -= block_k
    rows_per_chunk = _BWD_MAX_DQ_PARTIALS * bkdma
    if skp <= rows_per_chunk:
        dq32, dk, dv = _bwd_fused_stream_chunk(
            qp, kp, vp, dop, lse3, delta3, causal, block_q, block_k,
            q_len, bkdma, col_tile0=0)
        return (dq32 * scale).astype(qp.dtype), dk, dv
    dq32 = None
    dks, dvs = [], []
    for c0 in range(0, skp, rows_per_chunk):
        kc = kp[:, c0:c0 + rows_per_chunk]
        vc = vp[:, c0:c0 + rows_per_chunk]
        dqc, dkc, dvc = _bwd_fused_stream_chunk(
            qp, kc, vc, dop, lse3, delta3, causal, block_q, block_k,
            q_len, bkdma, col_tile0=c0 // block_k)
        dq32 = dqc if dq32 is None else dq32 + dqc
        dks.append(dkc)
        dvs.append(dvc)
    return ((dq32 * scale).astype(qp.dtype),
            jnp.concatenate(dks, axis=1), jnp.concatenate(dvs, axis=1))


def _bwd_fused_stream_chunk(qp, kp, vp, dop, lse3, delta3, causal,
                            block_q, block_k, q_len, bkdma, col_tile0):
    """One fused-backward pallas_call over a kv slice starting at absolute
    column tile `col_tile0`: grid (bh, n_kdma, n_q, n_sub); returns
    (dq_chunk f32 unscaled, dk_chunk, dv_chunk)."""
    bh, sp, d = qp.shape
    skp = kp.shape[1]
    n_q = sp // block_q
    n_k = skp // bkdma
    n_sub = bkdma // block_k
    kernel = functools.partial(_bwd_fused_kernel_stream, block_q=block_q,
                               block_k=block_k, causal=causal,
                               q_len=q_len, seq_q=sp, n_q=n_q,
                               n_sub=n_sub, col_tile0=col_tile0)
    col0_rows = col_tile0 * block_k

    if causal:
        def _iclamp(j, i):
            return jnp.maximum(i, (col0_rows + j * bkdma) // block_q)
    else:
        def _iclamp(j, i):
            return i
    q_map = lambda b, j, i, s_: (b, _iclamp(j, i), 0)
    stat_map = lambda b, j, i, s_: (b, 0, _iclamp(j, i))
    dqp_map = lambda b, j, i, s_: (j, b, _iclamp(j, i), 0)
    in_specs = [
        pl.BlockSpec((1, block_q, d), q_map),                   # q
        pl.BlockSpec((1, bkdma, d), lambda b, j, i, s_: (b, j, 0)),
        pl.BlockSpec((1, bkdma, d), lambda b, j, i, s_: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), q_map),                   # do
        pl.BlockSpec((1, 1, block_q), stat_map),                # lse
        pl.BlockSpec((1, 1, block_q), stat_map),                # delta
    ]
    args = [qp, kp, vp, dop, lse3, delta3]
    with _mosaic_ctx():
        dqp, dk, dv = pl.pallas_call(
            kernel,
            grid=(bh, n_k, n_q, n_sub),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d), dqp_map),
                pl.BlockSpec((1, bkdma, d), lambda b, j, i, s_: (b, j, 0)),
                pl.BlockSpec((1, bkdma, d), lambda b, j, i, s_: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_k, bh, sp, d), qp.dtype),
                jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                jax.ShapeDtypeStruct(vp.shape, vp.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bkdma, d), jnp.float32),
                pltpu.VMEM((bkdma, d), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            # the 16M scoped-VMEM default is a compiler guardrail, not the
            # hardware (v5e has 128M): bkdma=4096 needs ~19M of windows +
            # scratch and halves the dq-partial traffic vs bkdma=2048
            compiler_params=_tpu_compiler_params(
                vmem_limit_bytes=48 * 1024 * 1024),
            cost_estimate=_attn_cost(
                bh, sp, skp, d, qp.dtype.itemsize, causal, matmuls=5,
                extra_bytes=n_k * bh * sp * d * qp.dtype.itemsize,
                name="flash.bwd_fused_stream"),
            interpret=_interpret(),
        )(*args)
    # Σ_j ds̃·K (scale applied by the caller after cross-chunk
    # accumulation; q was pre-scaled — see _bwd_dkv_kernel docstring).
    # Under causal clamping the dead (j, i) partial slots were never
    # written (garbage): mask them out of the sum — the iota/compare
    # fuses into the reduce.
    if causal:
        row_tile = lax.broadcasted_iota(jnp.int32, (n_k, 1, sp, 1), 2) \
            // block_q
        imin = ((col0_rows + jnp.arange(n_k, dtype=jnp.int32) * bkdma)
                // block_q).reshape(n_k, 1, 1, 1)
        dqp = jnp.where(row_tile >= imin, dqp.astype(jnp.float32),
                        jnp.float32(0.0))
        dq = jnp.sum(dqp, axis=0)
    else:
        dq = jnp.sum(dqp, axis=0, dtype=jnp.float32)
    return dq, dk, dv


# Escape hatch for the default fused flat-schedule backward (r7): 'auto'
# runs the one-pass k-major kernel whenever its scratch fits the budget
# (below); 'split' forces the legacy dispatch — the two resident kernels
# (or the dq-partials streaming pass over the residency ceiling). The
# split resident pair is the bitwise-pinned reference the parity tests
# compare against. Read per call so tests can flip it via monkeypatched
# env (house pattern: ValueError names the variable).
ENV_FLASH_BWD = "PADDLE_TPU_FLASH_BWD"


def dense_bwd_mode() -> str:
    """'auto' (fused flat pass when its scratch fits) or 'split' (legacy
    two-kernel/dq-partials dispatch)."""
    return envs.get(ENV_FLASH_BWD)


def _dense_bwd_lo(n_q, n_k, causal, block_q, block_k):
    """Per-k-tile first live q-tile index (numpy, trace-time static): under
    causal, k tile j only receives gradient from q tiles at/past its own
    diagonal — i >= (j·bk)//bq, exactly the transpose of the forward's
    live set (j·bk <= (i+1)·bq − 1). K tiles past the last q row clamp to
    a single all-masked pair: its p is exactly 0, so dk/dv finalize to
    the zeros the split kernels produce and dq gains nothing, but the
    out blocks are still written (never garbage)."""
    import numpy as np
    if not causal:
        return np.zeros(n_k, dtype=np.int64)
    j = np.arange(n_k, dtype=np.int64)
    return np.minimum((j * block_k) // block_q, n_q - 1)


def _dense_bwd_schedule(n_q, n_k, causal, block_q, block_k):
    """K-major flat schedule over the live (k-tile, q-tile) pairs of a
    DENSE backward — the static-shape analogue of flash_varlen's
    _flat_schedule (no cu; bounds are closed-form, so the arrays are
    concrete at trace time). Returns int32 (ki, qi, first, last) scalar-
    prefetch arrays and n_flat; every step is live."""
    import numpy as np
    lo = _dense_bwd_lo(n_q, n_k, causal, block_q, block_k)
    spans = n_q - lo
    cum = np.concatenate([[0], np.cumsum(spans)])
    n_flat = int(cum[-1])
    s = np.arange(n_flat, dtype=np.int64)
    ki = np.searchsorted(cum, s, side="right") - 1
    qi = lo[ki] + (s - cum[ki])
    first = (s == cum[ki]).astype(np.int32)
    last = (s == cum[ki + 1] - 1).astype(np.int32)
    # int32: the package runs with x64 on, and int64 scalar-prefetch
    # operands break Mosaic's SMEM lowering
    return (jnp.asarray(ki, jnp.int32), jnp.asarray(qi, jnp.int32),
            jnp.asarray(first, jnp.int32), jnp.asarray(last, jnp.int32),
            n_flat)


def _bwd_fused_flat_kernel(ki_ref, qi_ref, first_ref, last_ref,
                           q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dq_ref, dk_ref, dv_ref, dk_s, dv_s, dq_s, *,
                           block_q, block_k, causal, scale, q_len, seq_q,
                           kv_len, seq_k):
    """Fused dK/dV/dQ in ONE pass per (k-tile, q-tile) pair: FLAT grid
    (bh, n_flat) in k-major order — the dense port of flash_varlen's
    _bwd_fused_kernel_varlen. Each pair fetches q/do/lse/delta and k/v
    ONCE and runs all five FA2 matmuls (S=QKᵀ, dP=dO·Vᵀ, dV=PᵀdO,
    dK+=dS̃ᵀQ̃, dQ+=dS̃·K) — the split two-kernel scheme fetched every
    block twice and ran seven (S and dP recomputed in the dq kernel),
    capping backward efficiency at 5/7 of forward.

    dK/dV accumulate in scratch across a k tile's consecutive steps
    (first/last flags). dQ accumulates in a PERSISTENT full-length
    scratch (dq_s, [seq_q, d] f32, zeroed at step 0 of each bh): a q
    tile's steps are NOT consecutive in k-major order, so the running
    partial is re-written to the dq out block on every step — the grid
    is sequential, so the final write-back of each presented block (the
    tile's LAST visit) carries the complete sum. Within one q tile the
    k contributions arrive in increasing j and within one k tile the q
    contributions in increasing i — the SAME f32 accumulation orders as
    the split kernels' inner loops, and _mask_scores' -1e30 overwrite
    on always-masked tiles is a p == 0 no-op — so the fused pass is
    bitwise-equal to the split pair at equal block sizes (pinned in
    tests). q arrives pre-scaled (see _bwd_dkv_kernel): the deferred
    ·scale rides each dq write-back, ·ln2 undoes q̃'s log2e on dK."""
    import numpy as np
    s_idx = pl.program_id(1)
    bq_i, bk_i = np.int32(block_q), np.int32(block_k)
    mask_q = q_len != seq_q
    mask_kv = kv_len != seq_k

    @pl.when(s_idx == 0)
    def _init_dq():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    @pl.when(first_ref[s_idx] == 1)
    def _init_dkv():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    qi = qi_ref[s_idx]
    ki = ki_ref[s_idx]
    qb = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    dob = do_ref[0]
    lseb = lse_ref[0, 0, :]
    deltab = delta_ref[0, 0, :]
    s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
    # iota mask on every step (no masked/unmasked split): the bwd is
    # MXU-bound — the VPU has slack — and interior tiles' where() is a
    # bitwise no-op (see _bwd_fused_kernel_stream)
    s = _mask_scores(s, qi * bq_i, ki * bk_i, causal,
                     row_limit=q_len if mask_q else None,
                     col_limit=kv_len if mask_kv else None)
    p = jnp.exp2(s - lseb[:, None])
    p_lo = p.astype(vb.dtype)
    dv_s[...] = dv_s[...] + jnp.dot(p_lo.T, dob,
                                    preferred_element_type=jnp.float32)
    dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
    ds = (p * (dp - deltab[:, None])).astype(vb.dtype)
    dk_s[...] = dk_s[...] + jnp.dot(ds.T, qb,
                                    preferred_element_type=jnp.float32)
    row = qi * bq_i
    dq_new = dq_s[pl.ds(row, block_q), :] + jnp.dot(
        ds, kb, preferred_element_type=jnp.float32)
    dq_s[pl.ds(row, block_q), :] = dq_new
    dq_ref[0] = (dq_new * scale).astype(dq_ref.dtype)

    @pl.when(last_ref[s_idx] == 1)
    def _flush_dkv():
        # q̃ carries an extra log2e (log2-domain scores); undo it on dK
        dk_ref[0] = (dk_s[...] * _LN2).astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


# Scoped-VMEM plan for the fused flat backward (same budget split as the
# varlen port it mirrors): the persistent [seq_q, d] f32 dQ accumulator is
# the big consumer, so block sizes are fitted per SHAPE and the Mosaic
# scoped-VMEM window is raised past the 16M guardrail accordingly.
_FLAT_BWD_VMEM_BUDGET = 52 * 1024 * 1024
_FLAT_BWD_VMEM_LIMIT = 80 * 1024 * 1024


def _bwd_flat_vmem_bytes(bq, bk, sp, d, itemsize):
    """Estimated scoped-VMEM footprint of one fused-flat grid step: f32
    scratch (persistent dq + dk/dv accumulators) plus the 6 live input
    windows and 3 out blocks (double-buffered) and the f32 score-tile
    temporaries."""
    scratch = 4 * (sp * d + 2 * bk * d)
    blocks = (2 * bq * d * itemsize      # q, do
              + 2 * bk * d * itemsize    # k, v
              + 2 * bq * 4               # lse, delta
              + bq * d * itemsize        # dq
              + 2 * bk * d * itemsize)   # dk, dv
    temps = 4 * bq * bk * 4              # s/p/dp/ds tiles
    return scratch + 2 * blocks + temps


def _shrink_block(b, n):
    """Next-smaller 128-aligned divisor of n below b (n is 128-aligned)."""
    b -= 128
    while b > 128 and n % b:
        b -= 128
    return max(b, 128)


def _fit_bwd_flat_blocks(block_q, block_k, sp, skp, d, itemsize):
    """_fit_block_t-style fitter (see decode_attention) for the fused flat
    backward: shrink the larger block side until the grid step fits the
    scoped-VMEM budget — hd >= 128 at big tiles would otherwise overrun
    scoped VMEM. Returns (block_q, block_k) or None when even 128x128
    does not fit (the [sp, d] dq scratch alone is over budget — very
    long sequences stay on the dq-partials streaming pass)."""
    bq, bk = block_q, block_k
    while _bwd_flat_vmem_bytes(bq, bk, sp, d, itemsize) \
            > _FLAT_BWD_VMEM_BUDGET:
        if bq <= 128 and bk <= 128:
            return None
        if bq >= bk and bq > 128:
            bq = _shrink_block(bq, sp)
        else:
            bk = _shrink_block(bk, skp)
    return bq, bk


def _bwd_fused_flat_call(qp, kp, vp, dop, lse3, delta3, causal, scale,
                         block_q, block_k, q_len, kv_len):
    """One fused-flat pallas_call over the whole padded backward: grid
    (bh, n_flat) with the (ki, qi, first, last) schedule scalar-prefetched.
    Each q/k/v/do block is fetched exactly once (the flat order revisits
    no pair), vs twice for the split pair — at S=32k this halves the HBM
    read traffic and removes the dq-partials reduction kernel, the lever
    behind the r05 bwd_eff=0.599 -> >=0.7 target."""
    bh, sp, d = qp.shape
    skp = kp.shape[1]
    it = qp.dtype.itemsize
    n_q, n_k = sp // block_q, skp // block_k
    ki_a, qi_a, first_a, last_a, n_flat = _dense_bwd_schedule(
        n_q, n_k, causal, block_q, block_k)
    kernel = functools.partial(_bwd_fused_flat_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               q_len=q_len, seq_q=sp, kv_len=kv_len,
                               seq_k=skp)
    with _mosaic_ctx():
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(bh, n_flat),
                in_specs=[
                    pl.BlockSpec((1, block_q, d),
                                 lambda b, s, ki, qi, f, l: (b, qi[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, ki, qi, f, l: (b, ki[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, ki, qi, f, l: (b, ki[s], 0)),
                    pl.BlockSpec((1, block_q, d),
                                 lambda b, s, ki, qi, f, l: (b, qi[s], 0)),
                    pl.BlockSpec((1, 1, block_q),
                                 lambda b, s, ki, qi, f, l: (b, 0, qi[s])),
                    pl.BlockSpec((1, 1, block_q),
                                 lambda b, s, ki, qi, f, l: (b, 0, qi[s])),
                ],
                out_specs=[
                    pl.BlockSpec((1, block_q, d),
                                 lambda b, s, ki, qi, f, l: (b, qi[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, ki, qi, f, l: (b, ki[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, ki, qi, f, l: (b, ki[s], 0)),
                ],
                scratch_shapes=[
                    pltpu.VMEM((block_k, d), jnp.float32),
                    pltpu.VMEM((block_k, d), jnp.float32),
                    pltpu.VMEM((sp, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, qp.dtype),
                jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                jax.ShapeDtypeStruct(vp.shape, vp.dtype),
            ],
            compiler_params=_tpu_compiler_params(
                vmem_limit_bytes=_FLAT_BWD_VMEM_LIMIT),
            cost_estimate=_cost_estimate(
                flops=10 * bh * n_flat * block_q * block_k * d,
                transcendentals=bh * n_flat * block_q * block_k,
                bytes_accessed=(bh * n_flat
                                * (2 * block_q + 2 * block_k) * d * it
                                + bh * (sp + 2 * skp) * d * it),
                name="flash.bwd_fused_flat"),
            interpret=_interpret(),
        )(ki_a, qi_a, first_a, last_a, qp, kp, vp, dop, lse3, delta3)
    return dq, dk, dv


def dense_bwd_schedule_stats(bh, sq, sk, d, dtype, causal,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K):
    """Which backward path _bwd_pallas_calls would run for this shape and
    its flat-schedule geometry — static (no tracing); recorded in
    BENCH_DETAIL next to the bwd_eff rungs."""
    item = jnp.dtype(dtype).itemsize
    block_q, block_k = _small_d_blocks(d, block_q, block_k)
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    sp = -(-sq // block_q) * block_q
    skp = -(-sk // block_k) * block_k
    stats = {"mode": dense_bwd_mode(), "bh": bh, "seq_q": sq, "seq_k": sk,
             "head_dim": d}
    fit = (_fit_bwd_flat_blocks(block_q, block_k, sp, skp, d, item)
           if stats["mode"] == "auto" else None)
    if fit is not None:
        bq, bk = fit
        n_q, n_k = sp // bq, skp // bk
        lo = _dense_bwd_lo(n_q, n_k, causal, bq, bk)
        n_flat = int(n_q * n_k - lo.sum())
        stats.update(path="fused_flat", block_q=bq, block_k=bk,
                     n_flat=n_flat, dead_pairs=n_q * n_k - n_flat,
                     fetches_per_block_pair=1, matmuls_per_pair=5,
                     dq_scratch_bytes=4 * sp * d)
    elif (2 * sp * d * item > STREAM_KV_BYTES
          or 2 * skp * d * item > STREAM_KV_BYTES):
        stats.update(path="fused_stream", block_q=block_q, block_k=block_k,
                     fetches_per_block_pair=1, matmuls_per_pair=5)
    else:
        stats.update(path="split_resident", block_q=block_q,
                     block_k=block_k, fetches_per_block_pair=2,
                     matmuls_per_pair=7)
    return stats


def _bwd_pallas_calls(qp, kp, vp, dop, lse3, delta3, causal, scale, block_q,
                      block_k, q_len, kv_len, q_prescaled=False):
    """Backward pallas_calls on already-padded [BH, Sp, D] operands.
    lse3/delta3: [BH, 1, Sp] f32. Returns padded (dq, dk, dv).

    The softmax scale is folded into q here (see _bwd_dkv_kernel): the
    kernels see q̃ = scale·q and compute dK = ds̃ᵀq̃ exactly; dQ applies
    the single deferred scale to its accumulator.

    Dispatch (r7): the fused FLAT k-major pass (_bwd_fused_flat_call) is
    the default whenever its scratch fits the fitted blocks; past that
    (very long S) the dq-partials streaming pass takes over; the split
    resident pair (dK/dV over k tiles, dQ over q tiles, whole opposing
    side in VMEM) remains as the bitwise-pinned PADDLE_TPU_FLASH_BWD=
    split fallback and the sub-residency leg of that mode."""
    bh, sp, d = qp.shape
    skp = kp.shape[1]
    item = kp.dtype.itemsize
    # log2-domain scores (see module constants): q̃ = scale·log2e·q, lse
    # converted to the log2 domain; the kernels' dK therefore comes out
    # log2e too large and is corrected by ·ln2 at finalize
    if not q_prescaled:
        qp = (qp.astype(jnp.float32) * (scale * _LOG2E)).astype(qp.dtype)
    lse3 = lse3 * _LOG2E
    if dense_bwd_mode() == "auto":
        # DEFAULT (r7): one fused k-major pass, each q/k/v/do block fetched
        # once feeding all five matmuls; bitwise-equal to the split pair at
        # equal blocks. Skipped only when even 128x128 tiles can't fit the
        # persistent [sp, d] dq scratch (very long S falls through to the
        # dq-partials streaming pass) or PADDLE_TPU_FLASH_BWD=split.
        fit = _fit_bwd_flat_blocks(block_q, block_k, sp, skp, d, item)
        if fit is not None:
            return _bwd_fused_flat_call(qp, kp, vp, dop, lse3, delta3,
                                        causal, scale, fit[0], fit[1],
                                        q_len, kv_len)
    if (2 * sp * d * item > STREAM_KV_BYTES
            or 2 * skp * d * item > STREAM_KV_BYTES):
        # the fused kernel streams both sides and does 5 matmuls per tile
        # pair (the old split kernels did 7 — see _bwd_fused_kernel_stream)
        return _bwd_fused_stream_call(qp, kp, vp, dop, lse3, delta3,
                                      causal, scale, block_q, block_k,
                                      q_len)
    dk = dv = None
    dq = None
    use_tri = causal and block_q == block_k
    tri = _tri_mask_const(block_q, block_k) if use_tri else None
    with _mosaic_ctx():
        if dk is None:
            tri_kv = use_tri and q_len == sp
            kv_grid = (bh, skp // block_k)
            in_specs = [
                pl.BlockSpec((1, sp, d), lambda b, j: (b, 0, 0)),     # q
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, sp, d), lambda b, j: (b, 0, 0)),     # do
                pl.BlockSpec((1, 1, sp), lambda b, j: (b, 0, 0)),     # lse
                pl.BlockSpec((1, 1, sp), lambda b, j: (b, 0, 0)),   # delta
            ]
            args = [qp, kp, vp, dop, lse3, delta3]
            if tri_kv:
                in_specs.append(pl.BlockSpec((block_q, block_k),
                                             lambda b, j: (0, 0)))
                args.append(tri)
            dk, dv = pl.pallas_call(
                functools.partial(_bwd_dkv_kernel, block_q=block_q,
                                  causal=causal, seq_q=sp,
                                  q_len=q_len, use_tri=tri_kv),
                grid=kv_grid,
                in_specs=in_specs,
                out_specs=[
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                    jax.ShapeDtypeStruct(vp.shape, vp.dtype),
                ],
                cost_estimate=_attn_cost(bh, sp, skp, d, item, causal,
                                         matmuls=4,
                                         name="flash.bwd_dkv"),
                interpret=_interpret(),
            )(*args)

        if dq is None:
            tri_q = use_tri and kv_len == skp
            q_grid = (bh, sp // block_q)
            in_specs = [
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, skp, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, skp, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            ]
            args = [qp, kp, vp, dop, lse3, delta3]
            if tri_q:
                in_specs.append(pl.BlockSpec((block_q, block_k),
                                             lambda b, i: (0, 0)))
                args.append(tri)
            dq = pl.pallas_call(
                functools.partial(_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, scale=scale, seq_k=skp,
                                  kv_len=kv_len, use_tri=tri_q),
                grid=q_grid,
                in_specs=in_specs,
                out_specs=pl.BlockSpec((1, block_q, d),
                                       lambda b, i: (b, i, 0)),
                out_shape=jax.ShapeDtypeStruct(qp.shape, qp.dtype),
                cost_estimate=_attn_cost(bh, sp, skp, d, item, causal,
                                         matmuls=3,
                                         name="flash.bwd_dq"),
                interpret=_interpret(),
            )(*args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k):
    # pre-scale once and save q̃ in the residuals: the backward's own
    # q-prep (another [BH, S, D] multiply + HBM round trip) is skipped
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    o, lse = _flash_fwd(qs, k, v, causal, None, block_q, block_k)
    return o, (qs, k, v, o, lse)


def _flash_attention_bwd(causal, scale, block_q, block_k, res, do):
    qs, k, v, o, lse = res
    return _flash_bwd_pallas(qs, k, v, o, lse, do, causal, scale, block_q,
                             block_k, q_prescaled=True)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Public entry. q: [B, S, H, D]; k/v: [B, S, Hkv, D] (GQA repeats kv)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def to_bh(x, seq):
        return x.transpose(0, 2, 1, 3).reshape(b * h, seq, d)

    o = _flash_attention(to_bh(q, s), to_bh(k, sk), to_bh(v, sk),
                         causal, float(scale), block_q, block_k)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# block-level entry points for ring attention (parallel/ring_attention.py):
# per-KV-block flash with the (o, lse) partials exposed so the caller can
# merge partial softmaxes across sequence shards, and the FA2 backward with
# caller-provided GLOBAL lse/delta (the identities hold per block when the
# statistics are global).
# ---------------------------------------------------------------------------

def flash_block_fwd(q, k, v, causal, scale, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K):
    """q/k/v: [BH, S, D]. Returns (o [BH, S, D], lse [BH, S] f32)."""
    return _flash_fwd(q, k, v, causal, float(scale), block_q, block_k)


def flash_block_bwd(q, k, v, do, lse, delta, causal, scale,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """FA2 backward for one KV block with global statistics.

    q/do: [BH, Sq, D]; k/v: [BH, Sk, D]; lse/delta: [BH, Sq] f32 computed
    over the FULL (all-block) attention. Sq/Sk must be 128-aligned (ring
    shards are; enforced here rather than padded because padding q rows
    with lse=0 would make exp(0-lse) contribute garbage to dk/dv).
    Returns (dq, dk, dv)."""
    bh, s, d = q.shape
    sk = k.shape[1]
    if s % 128 or sk % 128:
        raise ValueError(f"flash_block_bwd needs 128-aligned lengths, got "
                         f"q={s}, k={sk}")

    def fit_divisor(block, n):
        # largest 128-multiple <= block that divides n (n is 128-aligned)
        b = min(block, n)
        while n % b:
            b -= 128
        return b

    block_q = fit_divisor(block_q, s)
    block_k = fit_divisor(block_k, sk)
    lse3 = lse.reshape(bh, 1, s)
    delta3 = delta.reshape(bh, 1, s)
    return _bwd_pallas_calls(q, k, v, do, lse3, delta3, causal, float(scale),
                             block_q, block_k, q_len=s, kv_len=sk)
