"""Flash attention as a Pallas TPU kernel.

Ref: paddle/phi/kernels/gpu/flash_attn_kernel.cu (the reference dlopens its
FlashAttention-2 fork). TPU-native rewrite, not a translation:

- forward: Pallas kernel, online-softmax over KV tiles held in VMEM, fp32
  accumulators, MXU matmuls via jnp.dot(preferred_element_type=f32). The
  [S, S] score matrix never exists in HBM. Also emits the per-row logsumexp.
- backward: blockwise lax.scan in jnp using the saved logsumexp (the standard
  FA2 recomputation identities: dV = PᵀdO, dS = P∘(dP − rowsum(dO∘O)),
  dQ/dK from dS) — O(S·Bk) working set, fused by XLA. A hand-written Pallas
  backward is a further optimization, not a correctness need.

Layout [B, S, H, D] (the reference's), GQA via KV-head repeat.
interpret=True under CPU so the same code runs in tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal,
                scale, seq_k):
    import numpy as np
    bk_i = np.int32(block_k)  # i32 casts are belt-and-braces; the trace runs
    # under mosaic_trace_ctx (x64 disabled) — see _common.mosaic_trace_ctx
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    bq, d = q.shape
    bq_i = np.int32(bq)
    m = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    nblocks = np.int32(pl.cdiv(seq_k, block_k))
    if causal:
        # only blocks whose start <= last query position of this tile
        last_q = (qi + np.int32(1)) * bq_i - np.int32(1)
        nblocks = jnp.minimum(nblocks, last_q // bk_i + np.int32(1))

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * bk_i, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * bk_i, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            rows = qi * bq_i + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * bk_i + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(np.int32(0), nblocks, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # 2-D store ([1, BQ]); Mosaic fails to legalize 1-D vector stores.
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).T


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q, k, v: [BH, S, D] (same head count). Returns (o, lse)."""
    bh, s, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, sk)
    grid = (bh, pl.cdiv(s, block_q))
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale, seq_k=sk)
    with _mosaic_ctx():
        o, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
            ],
            interpret=_interpret(),
        )(q, k, v)
    return o, lse.reshape(bh, s)


def _flash_bwd(q, k, v, o, lse, do, causal, scale, block_k):
    """Blockwise FA2 backward in jnp. All [BH, S, D]."""
    bh, s, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    nblocks = sk // block_k
    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)  # [BH, S]

    kb = k.reshape(bh, nblocks, block_k, d).swapaxes(0, 1)
    vb = v.reshape(bh, nblocks, block_k, d).swapaxes(0, 1)
    pos_q = jnp.arange(s)

    def block_grads(carry, inp):
        dq_acc = carry
        j, k_j, v_j = inp
        s_j = jnp.einsum("bqd,bkd->bqk", q32, k_j.astype(jnp.float32)) * scale
        if causal:
            cols = j * block_k + jnp.arange(block_k)
            mask = pos_q[:, None] >= cols[None, :]
            s_j = jnp.where(mask[None], s_j, -1e30)
        p_j = jnp.exp(s_j - lse[:, :, None])                    # [BH, S, BK]
        dv_j = jnp.einsum("bqk,bqd->bkd", p_j, do32)
        dp_j = jnp.einsum("bqd,bkd->bqk", do32, v_j.astype(jnp.float32))
        ds_j = p_j * (dp_j - delta[:, :, None]) * scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds_j,
                                     k_j.astype(jnp.float32))
        dk_j = jnp.einsum("bqk,bqd->bkd", ds_j, q32)
        return dq_acc, (dk_j, dv_j)

    dq0 = jnp.zeros((bh, s, d), jnp.float32)
    dq, (dk_b, dv_b) = lax.scan(block_grads, dq0,
                                (jnp.arange(nblocks), kb, vb))
    dk = dk_b.swapaxes(0, 1).reshape(bh, sk, d)
    dv = dv_b.swapaxes(0, 1).reshape(bh, sk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_attention_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal, scale, block_k)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Public entry. q: [B, S, H, D]; k/v: [B, S, Hkv, D] (GQA repeats kv)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def to_bh(x, seq):
        return x.transpose(0, 2, 1, 3).reshape(b * h, seq, d)

    o = _flash_attention(to_bh(q, s), to_bh(k, sk), to_bh(v, sk),
                         causal, float(scale), block_q, block_k)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
