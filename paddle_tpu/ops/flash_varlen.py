"""Varlen (packed-sequence) flash attention as Pallas TPU kernels.

Ref: the reference's flash_attn_unpadded (python/paddle/nn/functional/
flash_attention.py + its FA2 varlen_fwd CUDA binding): packed sequences
[total_tokens, H, D] with cu_seqlens offsets, no cross-sequence attention.

TPU-native design — NOT the CUDA ragged-batch route. The packed stream is
treated as ONE long sequence per head, run through the streaming-KV flash
kernels (see flash_attention.py), and sequence isolation is enforced by a
per-token i32 CODE = segment_id << 20 | position:

- same-segment test: (code_a ^ code_b) < 2**20  (XOR clears equal high
  bits; any segment difference sets a bit >= 2**20)
- intra-segment causal: the code order IS (segment, position) lex order,
  so same_seg & (code_q >= code_k) masks exactly pos_q >= pos_k.

One i32 array per side replaces separate segment-id and position arrays —
half the mask DMA and two vector compares per tile. Padding rows carry
code PAD_CODE (a reserved segment) so they match nothing real; their
outputs/grads are sliced off and their upstream cotangents are zero, so
no masking epilogue is needed (see _flash_varlen_bwd).

Layouts follow the in-tree TPU convention to avoid in-kernel relayouts:
q-side codes are lane-replicated [T, 128] (a q tile reads [block_q, 128]
sublane-major), kv-side codes are sublane-replicated [8, T] (a kv tile
reads [1, block_k] lane-major); the [block_q, block_k] mask is then a
tile+broadcast compare with no transposes.

Limits (checked by the public wrapper, which falls back to the padded-
batch XLA path): < 1024 sequences per pack, < 2**20 tokens per sequence.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx
from .flash_attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, _fit_block,
                              _kv_clamp_map, _pad_rows, _q_clamp_map)


def _ck_from(kv_map):
    """kv-side code BlockSpec map from the k/v map (codes are [8, T]; drop
    the leading bh index, keep the — possibly clamped — tile index)."""
    return lambda b, i, j: (0, kv_map(b, i, j)[1])


def _cq_from(q_map):
    """q-side code BlockSpec map from the q map (codes are [T, 128])."""
    return lambda b, j, i: (q_map(b, j, i)[1], 0)

POS_BITS = 20
SEG_LIMIT = 1 << 10          # max sequences per pack (i32 headroom)
POS_LIMIT = 1 << POS_BITS    # max tokens per sequence
PAD_CODE = SEG_LIMIT << POS_BITS


def _segs_overlap(cq_ref, ck_ref, block_q, block_k):
    """Tile-level liveness: segments are contiguous runs of the packed
    stream, so the [BQ, BK] tile contains ANY same-segment pair iff the
    q tile's segment range intersects the k tile's. Four scalar loads +
    two compares per grid step; tiles that fail skip all compute (their
    DMA still runs — data-dependent DMA skipping would need scalar
    prefetch, a later optimization)."""
    seg_q0 = cq_ref[0, 0] >> POS_BITS
    seg_q1 = cq_ref[block_q - 1, 0] >> POS_BITS
    seg_k0 = ck_ref[0, 0] >> POS_BITS
    seg_k1 = ck_ref[0, block_k - 1] >> POS_BITS
    return jnp.logical_and(seg_q0 <= seg_k1, seg_k0 <= seg_q1)


def _tile_mask(s, cq_ref, ck_ref, causal):
    """Mask one [BQ, BK] score tile from the packed codes.

    cq_ref block: [block_q, 128] (lane-replicated); ck_ref block:
    [8, block_k] (sublane-replicated)."""
    bq, bk = s.shape
    cq = cq_ref[...]                        # [BQ, 128]
    ck = ck_ref[:1, :]                      # [1, BK]
    cqt = jnp.tile(cq, (1, bk // 128))      # [BQ, BK] lane-replicated
    same = (cqt ^ ck) < POS_LIMIT
    ok = same & (cqt >= ck) if causal else same
    return jnp.where(ok, s, -1e30)


def _fwd_kernel_varlen(q_ref, k_ref, v_ref, cq_ref, ck_ref, o_ref, lse_ref,
                       m_s, l_s, acc_s, *, block_k, causal, scale, n_k,
                       self_attn):
    """Streaming forward over the packed stream: grid (H, n_q, n_k), same
    online-softmax scratch scheme as flash_attention._fwd_kernel_stream.
    With self_attn+causal the caller clamps k/v (and ck) DMA above the
    global diagonal — valid because identical packing makes global order
    agree with (segment, position) order."""
    import numpy as np
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq = q_ref.shape[1]
    bq_i, bk_i = np.int32(bq), np.int32(block_k)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    needed = _segs_overlap(cq_ref, ck_ref, bq, block_k)
    if causal and self_attn:
        needed = jnp.logical_and(
            needed, ki * bk_i <= (qi + np.int32(1)) * bq_i - np.int32(1))

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        m = m_s[:, :1]
        l = l_s[:, :1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == np.int32(n_k - 1))
    def _finalize():
        m = m_s[:, :1]
        l = l_s[:, :1]
        o_ref[0] = (acc_s[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).T


def _bwd_dkv_kernel_varlen(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           cq_ref, ck_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                           block_q, causal, scale, n_q, self_attn):
    """Streaming dK/dV: grid (H, n_k, n_q); mirrors
    flash_attention._bwd_dkv_kernel_stream with the code mask. Padding q
    rows need no mask: their do (and hence delta) are zero-padded, so
    their contributions to dk/dv vanish identically."""
    import numpy as np
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    bk = k_ref.shape[1]
    bq_i, bk_i = np.int32(block_q), np.int32(bk)

    @pl.when(qi == 0)
    def _init():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    needed = _segs_overlap(cq_ref, ck_ref, block_q, bk)
    if causal and self_attn:
        needed = jnp.logical_and(
            needed, (qi + np.int32(1)) * bq_i > ki * bk_i)

    @pl.when(needed)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0, 0, :]
        deltab = delta_ref[0, 0, :]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        p = jnp.exp(s - lseb[:, None])
        p_lo = p.astype(v.dtype)
        dv_s[...] = dv_s[...] + jnp.dot(p_lo.T, dob,
                                        preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(v.dtype)
        dk_s[...] = dk_s[...] + jnp.dot(ds.T, qb,
                                        preferred_element_type=jnp.float32)

    @pl.when(qi == np.int32(n_q - 1))
    def _finalize():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _bwd_dq_kernel_varlen(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          cq_ref, ck_ref, dq_ref, dq_s, *, block_k, causal,
                          scale, n_k, self_attn):
    """Streaming dQ: grid (H, n_q, n_k); mirrors
    flash_attention._bwd_dq_kernel_stream with the code mask."""
    import numpy as np
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq = q_ref.shape[1]
    bq_i, bk_i = np.int32(bq), np.int32(block_k)

    @pl.when(ki == 0)
    def _init():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    needed = _segs_overlap(cq_ref, ck_ref, bq, block_k)
    if causal and self_attn:
        needed = jnp.logical_and(
            needed, ki * bk_i <= (qi + np.int32(1)) * bq_i - np.int32(1))

    @pl.when(needed)
    def _compute():
        qb = q_ref[0]
        dob = do_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        lseb = lse_ref[0, 0, :]
        deltab = delta_ref[0, 0, :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        p = jnp.exp(s - lseb[:, None])
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(kb.dtype)
        dq_s[...] = dq_s[...] + jnp.dot(ds, kb,
                                        preferred_element_type=jnp.float32)

    @pl.when(ki == np.int32(n_k - 1))
    def _finalize():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _expand_codes(code, t):
    """[T] i32 -> (q-side [T, 128] lane-replicated,
                   kv-side [8, T] sublane-replicated), padded to t rows
    with PAD_CODE."""
    n = code.shape[0]
    if t != n:
        code = jnp.pad(code, (0, t - n), constant_values=PAD_CODE)
    qside = jax.lax.broadcast_in_dim(code, (t, 128), (0,))
    kvside = jax.lax.broadcast_in_dim(code, (8, t), (1,))
    return qside.astype(jnp.int32), kvside.astype(jnp.int32)


def _codes_from_cu(cu, total):
    """cu [B+1] i32 cumulative offsets -> packed [total] codes."""
    t = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu, t, side="right").astype(jnp.int32) - 1
    pos = t - cu[seg]
    return (seg << POS_BITS) | pos


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_varlen(q, k, v, code_q, code_k, causal, scale, block_q, block_k,
                  self_attn):
    o, _ = _flash_varlen_fwd_impl(q, k, v, code_q, code_k, causal, scale,
                                  block_q, block_k, self_attn)
    return o


def _flash_varlen_fwd_impl(q, k, v, code_q, code_k, causal, scale, block_q,
                           block_k, self_attn):
    """q/k/v: [H, T, D] packed; code_q/k: [T] i32. Returns (o, lse)."""
    h, t, d = q.shape
    tk = k.shape[1]
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, tk)
    qp, _ = _pad_rows(q, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    tp, tkp = qp.shape[1], kp.shape[1]
    cq2d, _ = _expand_codes(code_q, tp)
    _, ck2d = _expand_codes(code_k, tkp)
    n_k = tkp // block_k
    kv_map = _kv_clamp_map(block_q, block_k, causal and self_attn)
    ck_map = _ck_from(kv_map)
    kernel = functools.partial(_fwd_kernel_varlen, block_k=block_k,
                               causal=causal, scale=scale, n_k=n_k,
                               self_attn=self_attn)
    with _mosaic_ctx():
        o, lse = pl.pallas_call(
            kernel,
            grid=(h, tp // block_q, n_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), kv_map),
                pl.BlockSpec((1, block_k, d), kv_map),
                pl.BlockSpec((block_q, 128), lambda b, i, j: (i, 0)),
                pl.BlockSpec((8, block_k), ck_map),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((h, 1, tp), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=_interpret(),
        )(qp, kp, vp, cq2d, ck2d)
    return o[:, :t], lse.reshape(h, tp)[:, :t]


def _flash_varlen_fwd(q, k, v, code_q, code_k, causal, scale, block_q,
                      block_k, self_attn):
    o, lse = _flash_varlen_fwd_impl(q, k, v, code_q, code_k, causal, scale,
                                    block_q, block_k, self_attn)
    return o, (q, k, v, code_q, code_k, o, lse)


def _flash_varlen_bwd(causal, scale, block_q, block_k, self_attn, res, do):
    q, k, v, code_q, code_k, o, lse = res
    h, t, d = q.shape
    tk = k.shape[1]
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, tk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp, _ = _pad_rows(q, block_q)
    dop, _ = _pad_rows(do, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    tp, tkp = qp.shape[1], kp.shape[1]
    lse3, _ = _pad_rows(lse.reshape(h, t, 1), block_q)
    delta3, _ = _pad_rows(delta.reshape(h, t, 1), block_q)
    lse3 = lse3.reshape(h, 1, tp)
    delta3 = delta3.reshape(h, 1, tp)
    cq2d, _ = _expand_codes(code_q, tp)
    _, ck2d = _expand_codes(code_k, tkp)
    n_q, n_k = tp // block_q, tkp // block_k
    cc = causal and self_attn

    # dK/dV: grid (h, n_k, n_q); q-side DMA clamped below the diagonal
    q_map = _q_clamp_map(block_q, block_k, cc)
    stat_map = _q_clamp_map(block_q, block_k, cc, stat=True)
    cq_map = _cq_from(q_map)
    with _mosaic_ctx():
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel_varlen, block_q=block_q,
                              causal=causal, scale=scale, n_q=n_q,
                              self_attn=self_attn),
            grid=(h, n_k, n_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), q_map),
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_q, d), q_map),
                pl.BlockSpec((1, 1, block_q), stat_map),
                pl.BlockSpec((1, 1, block_q), stat_map),
                pl.BlockSpec((block_q, 128), cq_map),
                pl.BlockSpec((8, block_k), lambda b, j, i: (0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(kp.shape, k.dtype),
                jax.ShapeDtypeStruct(vp.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            interpret=_interpret(),
        )(qp, kp, vp, dop, lse3, delta3, cq2d, ck2d)

        kv_map = _kv_clamp_map(block_q, block_k, cc)
        ck_map = _ck_from(kv_map)
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel_varlen, block_k=block_k,
                              causal=causal, scale=scale, n_k=n_k,
                              self_attn=self_attn),
            grid=(h, n_q, n_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), kv_map),
                pl.BlockSpec((1, block_k, d), kv_map),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((block_q, 128), lambda b, i, j: (i, 0)),
                pl.BlockSpec((8, block_k), ck_map),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_interpret(),
        )(qp, kp, vp, dop, lse3, delta3, cq2d, ck2d)
    return dq[:, :t], dk[:, :tk], dv[:, :tk], None, None


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


def flash_varlen_attention(q, k, v, cu_seqlens_q, cu_seqlens_k, scale,
                           causal, self_attn=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Kernel-backed packed varlen attention.

    q: [total_q, H, D]; k/v: [total_k, Hkv, D] (GQA repeats kv heads);
    cu_seqlens_*: [B+1] i32 cumulative offsets. Returns [total_q, H, D].
    self_attn=True (auto-detected from object identity of the cu arrays)
    additionally skips DMA/compute of above-diagonal tiles under causal.
    """
    if self_attn is None:
        self_attn = cu_seqlens_q is cu_seqlens_k
    tq, h, d = q.shape
    tk = k.shape[0]
    hkv = k.shape[1]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    cu_q = cu_seqlens_q.astype(jnp.int32)
    code_q = _codes_from_cu(cu_q, tq)
    if self_attn:
        code_k = code_q
    else:
        code_k = _codes_from_cu(cu_seqlens_k.astype(jnp.int32), tk)
    qh = q.transpose(1, 0, 2)
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    o = _flash_varlen(qh, kh, vh, code_q, code_k, causal, float(scale),
                      block_q, block_k, bool(self_attn))
    return o.transpose(1, 0, 2)
