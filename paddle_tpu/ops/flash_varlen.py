"""Varlen (packed-sequence) flash attention as Pallas TPU kernels.

Ref: the reference's flash_attn_unpadded (python/paddle/nn/functional/
flash_attention.py + its FA2 varlen_fwd CUDA binding): packed sequences
[total_tokens, H, D] with cu_seqlens offsets, no cross-sequence attention.

TPU-native design — NOT the CUDA ragged-batch route. The packed stream is
treated as ONE long sequence per head, run through the streaming-KV flash
kernels (see flash_attention.py), and sequence isolation is enforced by a
per-token i32 CODE = segment_id << 20 | position:

- same-segment test: (code_a ^ code_b) < 2**20  (XOR clears equal high
  bits; any segment difference sets a bit >= 2**20)
- intra-segment causal: the code order IS (segment, position) lex order,
  so same_seg & (code_q >= code_k) masks exactly pos_q >= pos_k.

One i32 array per side replaces separate segment-id and position arrays —
half the mask DMA and two vector compares per tile. Padding rows carry
code PAD_CODE (a reserved segment) so they match nothing real; their
outputs/grads are sliced off and their upstream cotangents are zero, so
no masking epilogue is needed (see _flash_varlen_bwd).

Layouts follow the in-tree TPU convention to avoid in-kernel relayouts:
q-side codes are lane-replicated [T, 128] (a q tile reads [block_q, 128]
sublane-major), kv-side codes are sublane-replicated [8, T] (a kv tile
reads [1, block_k] lane-major); the [block_q, block_k] mask is then a
tile+broadcast compare with no transposes.

Limits (checked by the public wrapper, which falls back to the padded-
batch XLA path): < 1024 sequences per pack, < 2**20 tokens per sequence.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import tpu_compiler_params as _tpu_compiler_params
from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx
from .flash_attention import DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, _LN2, \
    _fit_block, _pad_rows

POS_BITS = 20
SEG_LIMIT = 1 << 10          # max sequences per pack (i32 headroom)
POS_LIMIT = 1 << POS_BITS    # max tokens per sequence
PAD_CODE = SEG_LIMIT << POS_BITS


def _live_col_tiles(cu_rows, cu_cols, n_tiles, block_rows, block_cols,
                    total_rows):
    """Per ROW tile, the contiguous [lo, hi] range of COLUMN tiles holding
    any same-segment pair: segments are contiguous runs of the packed
    stream, so row tile i (rows [i*br, (i+1)*br)) spans segments
    seg(first_row)..seg(last_row), whose columns occupy
    cu_cols[seg_first] .. cu_cols[seg_last + 1] - 1 — one contiguous
    column range. These bounds are SCALAR-PREFETCHED into the kernels'
    index maps, so tiles outside the range are never DMA'd or computed
    (splash-attention-style data-dependent scheduling)."""
    i = jnp.arange(n_tiles)
    r0 = jnp.clip(i * block_rows, 0, total_rows - 1)
    r1 = jnp.clip((i + 1) * block_rows - 1, 0, total_rows - 1)
    seg0 = jnp.searchsorted(cu_rows, r0, side="right").astype(jnp.int32) - 1
    seg1 = jnp.searchsorted(cu_rows, r1, side="right").astype(jnp.int32) - 1
    lo = (cu_cols[seg0] // block_cols).astype(jnp.int32)
    hi = ((jnp.maximum(cu_cols[seg1 + 1], cu_cols[seg1] + 1) - 1)
          // block_cols).astype(jnp.int32)
    return lo, jnp.maximum(hi, lo)


def _tile_mask(s, cq_ref, ck_ref, causal):
    """Mask one [BQ, BK] score tile from the packed codes.

    cq_ref block: [block_q, 128] (lane-replicated); ck_ref block:
    [8, block_k] (sublane-replicated)."""
    bq, bk = s.shape
    cq = cq_ref[...]                        # [BQ, 128]
    ck = ck_ref[:1, :]                      # [1, BK]
    cqt = jnp.tile(cq, (1, bk // 128))      # [BQ, BK] lane-replicated
    same = (cqt ^ ck) < POS_LIMIT
    ok = same & (cqt >= ck) if causal else same
    return jnp.where(ok, s, jnp.float32(-1e30))


def _flat_schedule(lo, hi, n_q, n_flat):
    """Front-packed flat schedule over the LIVE (q-tile, k-tile) pairs.

    The rectangular grid (n_q, per-tile-span-bound) spends one grid step
    (~1.3 µs of fixed Mosaic cost) on every dead (clamped) slot; on short
    -sequence packs dead steps outnumber live ones ~30:1 and dominate the
    kernel (measured: the 16-seq/16k pack ran 1280 steps for ~40 live
    tiles). Flattening packs the live pairs first: step s works on
    (qi[s], ki[s]); the dead remainder collapses to a clamped tail that
    re-presents the last window (no DMA, no compute). All arrays are
    computed IN-GRAPH from cu, so the schedule is jit-correct for any
    cu values at the same shapes; n_flat is the same static bound the
    rectangular grid used (n_q x span bound), so worst-case work is
    unchanged."""
    spans = (hi - lo + 1).astype(jnp.int32)
    cum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                           jnp.cumsum(spans).astype(jnp.int32)])
    s = jnp.arange(n_flat, dtype=jnp.int32)
    qi = jnp.clip(jnp.searchsorted(cum, s, side="right") - 1,
                  0, n_q - 1).astype(jnp.int32)
    ki = jnp.clip(lo[qi] + (s - cum[qi]), lo[qi], hi[qi]).astype(jnp.int32)
    live = (s < cum[n_q]).astype(jnp.int32)
    first = ((s == cum[qi]) & (live == 1)).astype(jnp.int32)
    last = ((s == cum[qi + 1] - 1) & (live == 1)).astype(jnp.int32)
    return qi, ki, first, last, live


def _fwd_kernel_varlen(qi_ref, ki_ref, first_ref, last_ref, live_ref,
                       q_ref, k_ref, v_ref, cq_ref, ck_ref,
                       o_ref, lse_ref, m_s, l_s, acc_s, *, causal, scale):
    """Streaming forward over the packed stream: FLAT grid (H, n_flat),
    one live (q-tile, k-tile) pair per step (_flat_schedule), classic
    ONLINE-softmax scratch scheme (running max + alpha rescale). NOTE:
    flash_attention's dense kernels moved to the r5 fixed-base scheme
    (tile-0-anchored exponent base, no rescale) — the varlen kernels
    still rescale online; the two no longer share softmax semantics.
    Init/finalize are driven by the scalar-prefetched first/last flags
    (a q tile's steps are consecutive in the flat order); masking needs
    no positional bookkeeping — the segment codes carry it."""
    s_idx = pl.program_id(1)

    @pl.when(first_ref[s_idx] == 1)
    def _init():
        m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    @pl.when(live_ref[s_idx] == 1)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        m = m_s[:, :1]
        l = l_s[:, :1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(last_ref[s_idx] == 1)
    def _finalize():
        m = m_s[:, :1]
        l = l_s[:, :1]
        # a row with NO live key (cross-attn q segment whose k side is
        # empty) ends with m == -1e30 (the mask overwrite value): its
        # online softmax degenerated to p=1 over masked slots. Its true
        # output is all-padding -> 0, and its lse must be a value that
        # makes the backward's p = exp(s + bias - lse) vanish (bias is
        # -1e30, so any lse >> -1e30 does; 0 keeps it finite).
        dead = m <= jnp.float32(-1e29)
        o_ref[0] = jnp.where(
            dead, jnp.float32(0.0),
            acc_s[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(
            dead, jnp.float32(0.0),
            m + jnp.log(jnp.maximum(l, 1e-30))).T


def _bwd_bounds(cu_q, cu_k, n_k, block_q, block_k, tk, causal, self_attn):
    """Live Q-tile [lo, hi] per K tile (the backward's k-major
    orientation), with the causal START folded in for self-attention
    packing: k tile j only receives gradient from q rows at or past its
    own diagonal, so the live run begins at max(segment start,
    (j*block_k)//block_q). For self-attention this is EXACTLY the
    transpose of _fwd_bounds' live set (j*block_k <= (i+1)*block_q - 1
    iff (j*block_k)//block_q <= i), so the flat backward walks the same
    live pairs as the forward, k-major."""
    lo, hi = _live_col_tiles(cu_k, cu_q, n_k, block_k, block_q, tk)
    if causal and self_attn:
        j = jnp.arange(n_k, dtype=jnp.int32)
        lo = jnp.maximum(lo, ((j * block_k) // block_q).astype(jnp.int32))
        hi = jnp.maximum(hi, lo)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _bwd_fused_kernel_varlen(ki_ref, qi_ref, first_ref, last_ref, live_ref,
                             q_ref, k_ref, v_ref, do_ref, lse_ref,
                             delta_ref, cq_ref, ck_ref, dq_ref, dk_ref,
                             dv_ref, dk_s, dv_s, dq_s, *, causal, scale,
                             nh, block_q, block_k, tp):
    """Fused dK/dV/dQ in ONE streaming pass per live tile: FLAT grid
    (H/nh, n_flat) in k-major order (_flat_schedule over the per-k-tile
    live q ranges), the varlen analogue of the dense path's
    _bwd_fused_kernel_stream. Each live (k-tile, q-tile) pair fetches
    q/do/lse/delta and k/v ONCE and runs all five matmuls (s, dv, dp,
    dk, dq) — the split two-kernel scheme fetched every block twice and
    ran seven matmuls (s and dp recomputed in the dq kernel).

    This is also the rows-stacked head-fusion port to the backward
    (cf. _fwd_kernel_varlen_stacked): `nh` heads ride one grid step, the
    segment mask is built ONCE per step as an additive f32 bias (it is
    head-independent), and short-segment packs amortize the per-step
    fixed cost across heads. Adding -1e30 to a finite masked score is
    bitwise-identical in f32 to overwriting it with -1e30 (|s| < 1e23
    is absorbed; +0.0 is exact), so the fused kernel matches the split
    kernels bit-for-bit at equal block sizes.

    dK/dV accumulate in scratch across a k tile's consecutive live steps
    (first/last flags) exactly like the split kernel. dQ accumulates in
    a PERSISTENT full-length scratch (dq_s, [nh*tp, d] f32, zeroed once
    at step 0): a q tile's steps are NOT consecutive in k-major order,
    so the running partial is re-written to the dq out block on every
    live step — the grid is sequential, so the final write-back of each
    presented block (the tile's LAST visit) carries the complete sum.
    Padding q rows need no epilogue: their do/delta are zero-padded, so
    dk/dv contributions vanish; pad k columns mask against every real q
    row via the codes."""
    import numpy as np
    s_idx = pl.program_id(1)
    bq = np.int32(block_q)

    @pl.when(s_idx == 0)
    def _init_dq():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    @pl.when(first_ref[s_idx] == 1)
    def _init_dkv():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    @pl.when(live_ref[s_idx] == 1)
    def _compute():
        qi = qi_ref[s_idx]
        cq = cq_ref[:, :1]
        ck = ck_ref[:1, :]
        same = (cq ^ ck) < POS_LIMIT
        ok = same & (cq >= ck) if causal else same
        bias = jnp.where(ok, jnp.float32(0.0), jnp.float32(-1e30))
        for hh in range(nh):
            qb = q_ref[hh]
            kb = k_ref[hh]
            vb = v_ref[hh]
            dob = do_ref[hh]
            lseb = lse_ref[hh, 0, :]
            deltab = delta_ref[hh, 0, :]
            sl = slice(hh * block_k, (hh + 1) * block_k)
            s = jnp.dot(qb, kb.T,
                        preferred_element_type=jnp.float32) * scale + bias
            p = jnp.exp(s - lseb[:, None])
            p_lo = p.astype(vb.dtype)
            dv_s[sl] = dv_s[sl] + jnp.dot(
                p_lo.T, dob, preferred_element_type=jnp.float32)
            dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
            ds = (p * (dp - deltab[:, None]) * scale).astype(vb.dtype)
            dk_s[sl] = dk_s[sl] + jnp.dot(
                ds.T, qb, preferred_element_type=jnp.float32)
            row = qi * bq + np.int32(hh * tp)
            dq_new = dq_s[pl.ds(row, block_q), :] + jnp.dot(
                ds, kb, preferred_element_type=jnp.float32)
            dq_s[pl.ds(row, block_q), :] = dq_new
            dq_ref[hh] = dq_new.astype(dq_ref.dtype)

    @pl.when(last_ref[s_idx] == 1)
    def _flush_dkv():
        for hh in range(nh):
            sl = slice(hh * block_k, (hh + 1) * block_k)
            dk_ref[hh] = dk_s[sl].astype(dk_ref.dtype)
            dv_ref[hh] = dv_s[sl].astype(dv_ref.dtype)


def _bwd_dkv_flat_kernel(ki_ref, qi_ref, first_ref, last_ref, live_ref,
                         q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         cq_ref, ck_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                         causal, scale):
    """Split-kernel dK/dV on the FLAT k-major live-tile schedule: grid
    (H, n_flat), one live (k-tile, q-tile) pair per step. Fallback for
    shapes where the fused kernel's persistent dQ scratch does not fit
    scoped VMEM (_bwd_fused_nh == 0 — very long packed streams); still
    skips every dead tile the old rectangular (H, n_k, n_q) grid burned
    a predicated step on."""
    s_idx = pl.program_id(1)

    @pl.when(first_ref[s_idx] == 1)
    def _init():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    @pl.when(live_ref[s_idx] == 1)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0, 0, :]
        deltab = delta_ref[0, 0, :]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        p = jnp.exp(s - lseb[:, None])
        p_lo = p.astype(v.dtype)
        dv_s[...] = dv_s[...] + jnp.dot(p_lo.T, dob,
                                        preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(v.dtype)
        dk_s[...] = dk_s[...] + jnp.dot(ds.T, qb,
                                        preferred_element_type=jnp.float32)

    @pl.when(last_ref[s_idx] == 1)
    def _finalize():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _bwd_dq_flat_kernel(qi_ref, ki_ref, first_ref, last_ref, live_ref,
                        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        cq_ref, ck_ref, dq_ref, dq_s, *, causal, scale):
    """Split-kernel dQ on the FLAT q-major live-tile schedule (the same
    _flat_schedule arrays the forward runs): grid (H, n_flat). Fallback
    companion of _bwd_dkv_flat_kernel."""
    s_idx = pl.program_id(1)

    @pl.when(first_ref[s_idx] == 1)
    def _init():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    @pl.when(live_ref[s_idx] == 1)
    def _compute():
        qb = q_ref[0]
        dob = do_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        lseb = lse_ref[0, 0, :]
        deltab = delta_ref[0, 0, :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, cq_ref, ck_ref, causal)
        p = jnp.exp(s - lseb[:, None])
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(kb.dtype)
        dq_s[...] = dq_s[...] + jnp.dot(ds, kb,
                                        preferred_element_type=jnp.float32)

    @pl.when(last_ref[s_idx] == 1)
    def _finalize():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


# Scoped-VMEM plan for the fused backward. The persistent dQ accumulator
# (nh * padded_total_q rows of f32) is the big consumer, so the head
# grouping is fitted per SHAPE, not just per dtype; the Mosaic scoped-
# VMEM window is raised accordingly (the dense fused backward already
# runs at 48 MB — see flash_attention._bwd_fused_stream_chunk).
_FUSED_BWD_VMEM_BUDGET = 52 * 1024 * 1024
_BWD_VMEM_LIMIT = 80 * 1024 * 1024


def _bwd_fused_vmem_bytes(nh, itemsize, bq, bk, d, tp):
    """Estimated scoped-VMEM footprint of one fused-backward grid step:
    f32 scratch (persistent dq + dk/dv accumulators) plus double-buffered
    in/out blocks."""
    scratch = 4 * (nh * tp * d + 2 * nh * bk * d)
    blocks = (2 * nh * bq * d * itemsize      # q, do
              + 2 * nh * bk * d * itemsize    # k, v
              + 2 * nh * bq * 4               # lse, delta
              + bq * 128 * 4 + 8 * bk * 4     # code tiles
              + nh * bq * d * itemsize        # dq
              + 2 * nh * bk * d * itemsize)   # dk, dv
    temps = 4 * bq * bk * 4                   # s/p/dp/ds tiles
    return scratch + 2 * blocks + temps


def _bwd_fused_nh(h, itemsize, d, bq, bk, tp):
    """Heads fused per fused-backward grid step: largest power-of-two
    divisor of h whose footprint (incl. the [nh*tp, d] persistent dQ
    scratch) fits the budget. Returns 0 when not even nh=1 fits — the
    caller falls back to the split flat kernels, which stream dQ through
    a per-tile scratch instead."""
    for cand in (8, 4, 2, 1):
        if h % cand == 0 and _bwd_fused_vmem_bytes(
                cand, itemsize, bq, bk, d, tp) <= _FUSED_BWD_VMEM_BUDGET:
            return cand
    return 0


def _fwd_kernel_varlen_stacked(qi_ref, ki_ref, first_ref, last_ref, live_ref,
                               q_ref, k_ref, v_ref, cq_ref, ck_ref,
                               o_ref, lse_ref, s_s, m_s, l_s, acc_s, *,
                               causal, nh, block_q):
    """Rows-stacked head-fused forward: one grid step processes `nh` heads
    of the SAME live (q-tile, k-tile) pair, with every head's score tile
    stacked along the ROW axis of one scratch buffer so the online-softmax
    chain (rowmax -> alpha -> exp2 -> rowsum -> rescale) runs ONCE per
    step for all nh heads.

    Why: the chain costs ~1-1.6 us of serial (non-overlapped) VPU latency
    per score chunk REGARDLESS of chunk size (measured on v5e: 1.1 us at
    256^2, 1.6 at 512^2, 1.5 at 1024^2 — row-parallel, latency-bound),
    and Mosaic does not overlap it with the MXU matmuls. Per-head kernels
    pay it once per (chunk, head); stacking pays it once per chunk. The
    mask is also head-independent and is built once as an additive f32
    bias. Best for SHORT-segment packs, where small tiles (low waste)
    make the chain the dominant cost; long-segment packs keep the
    per-head streaming kernel (full-rate 1024^2 matmuls, waste ~0).
    """
    bq = block_q
    s_idx = pl.program_id(1)

    @pl.when(first_ref[s_idx] == 1)
    def _init():
        m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    @pl.when(live_ref[s_idx] == 1)
    def _compute():
        cq = cq_ref[:, :1]
        ck = ck_ref[:1, :]
        same = (cq ^ ck) < POS_LIMIT
        ok = same & (cq >= ck) if causal else same
        bias = jnp.where(ok, jnp.float32(0.0), jnp.float32(-1e30))
        for hh in range(nh):
            s_s[hh * bq:(hh + 1) * bq] = jnp.dot(
                q_ref[hh], k_ref[hh].T,
                preferred_element_type=jnp.float32) + bias
        s = s_s[...]
        m = m_s[:, :1]
        l = l_s[:, :1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp2(m - m_new)
        p = jnp.exp2(s - m_new)
        l_s[...] = jnp.broadcast_to(
            l * alpha + p.sum(axis=-1, keepdims=True), l_s.shape)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        pb = p.astype(v_ref.dtype)
        for hh in range(nh):
            sl = slice(hh * bq, (hh + 1) * bq)
            acc_s[sl] = acc_s[sl] * alpha[sl] + jnp.dot(
                pb[sl], v_ref[hh], preferred_element_type=jnp.float32)

    @pl.when(last_ref[s_idx] == 1)
    def _finalize():
        m = m_s[:, :1]
        l = l_s[:, :1]
        big_o = acc_s[...] / jnp.maximum(l, 1e-30)
        big_lse = (m + jnp.log2(jnp.maximum(l, 1e-30))) * _LN2
        for hh in range(nh):
            sl = slice(hh * bq, (hh + 1) * bq)
            o_ref[hh] = big_o[sl].astype(o_ref.dtype)
            lse_ref[hh] = big_lse[sl].T


# Scoped-VMEM budget for one stacked grid step. v5e exposes ~16 MB of
# scoped VMEM to a Mosaic kernel; leave headroom for compiler temporaries.
# (Measured: f32 inputs at nh=8 request 20.72 MB and fail to compile;
# bf16 at nh=8 is ~13.9 MB and compiles.)
_STACKED_VMEM_BUDGET = 14 * 1024 * 1024


def _stacked_vmem_bytes(nh, itemsize, bq, bk, d):
    """Estimated scoped-VMEM footprint of one stacked-kernel grid step:
    f32 scratch (scores + m/l columns + acc) plus double-buffered in/out
    blocks (q, k, v, code tiles, o, lse)."""
    scratch = 4 * (nh * bq * bk + 2 * nh * bq * 128 + nh * bq * d)
    blocks = (nh * bq * d * itemsize          # q
              + 2 * nh * bk * d * itemsize    # k, v
              + bq * 128 * 4 + 8 * bk * 4     # code tiles
              + nh * bq * d * itemsize        # o
              + nh * bq * 4)                  # lse
    return scratch + 2 * blocks


def _stacked_nh(h, itemsize=2, d=128, bq=None, bk=None):
    """Heads fused per grid step: largest power-of-two divisor of h that
    is <= 8 (powers of two keep the stacked scratch row count
    tile-aligned; non-power-of-two head counts amortize less) AND whose
    grid-step footprint fits the scoped-VMEM budget — f32 inputs double
    the block bytes, so nh=8 that compiles in bf16 OOMs at f32 (advisor
    r4 finding). Returns 0 when no grouping fits (caller falls back to
    the per-head streaming kernel)."""
    bq = STACKED_BLOCK_Q if bq is None else bq
    bk = STACKED_BLOCK_K if bk is None else bk
    for cand in (8, 4, 2, 1):
        if h % cand == 0 and _stacked_vmem_bytes(
                cand, itemsize, bq, bk, d) <= _STACKED_VMEM_BUDGET:
            return cand
    return 0


def _flash_varlen_fwd_stacked(q, k, v, cu_q, causal, scale, block_q,
                              block_k, n_flat_hint=None):
    """Stacked-kernel forward for SELF-ATTENTION short-segment packs.

    q/k/v: [H, T, D] packed; q is pre-scale-folded HERE (scale*log2e into
    q once — the kernel softmax runs in the exp2 domain; lse is returned
    in the natural-log domain for vjp compatibility)."""
    from .flash_attention import _LOG2E
    h, t, d = q.shape
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    it = jnp.dtype(q.dtype).itemsize
    q = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qp, _ = _pad_rows(q, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    tp, tkp = qp.shape[1], kp.shape[1]
    code = _codes_from_cu(cu_q, t)
    cq2d, _ = _expand_codes(code, tp)
    _, ck2d = _expand_codes(code, tkp)
    n_q, n_k = tp // block_q, tkp // block_k
    lo, hi = _fwd_bounds(cu_q, cu_q, n_q, block_q, block_k, t, causal, True)
    n_flat = min(n_flat_hint, n_q * n_k) if n_flat_hint else n_q * n_k
    qi_a, ki_a, first_a, last_a, live_a = _flat_schedule(lo, hi, n_q, n_flat)
    nh = _stacked_nh(h, jnp.dtype(q.dtype).itemsize, d, block_q, block_k)
    if nh == 0:
        raise ValueError(
            "stacked varlen kernel does not fit VMEM at this dtype/shape; "
            "selection should have fallen back to the streaming kernel")
    kernel = functools.partial(_fwd_kernel_varlen_stacked, causal=causal,
                               nh=nh, block_q=block_q)
    with _mosaic_ctx():
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=5,
                grid=(h // nh, n_flat),
                in_specs=[
                    pl.BlockSpec((nh, block_q, d),
                                 lambda g, s, qi, ki, f, l, lv: (g, qi[s], 0)),
                    pl.BlockSpec((nh, block_k, d),
                                 lambda g, s, qi, ki, f, l, lv: (g, ki[s], 0)),
                    pl.BlockSpec((nh, block_k, d),
                                 lambda g, s, qi, ki, f, l, lv: (g, ki[s], 0)),
                    pl.BlockSpec((block_q, 128),
                                 lambda g, s, qi, ki, f, l, lv: (qi[s], 0)),
                    pl.BlockSpec((8, block_k),
                                 lambda g, s, qi, ki, f, l, lv: (0, ki[s])),
                ],
                out_specs=[
                    pl.BlockSpec((nh, block_q, d),
                                 lambda g, s, qi, ki, f, l, lv: (g, qi[s], 0)),
                    pl.BlockSpec((nh, 1, block_q),
                                 lambda g, s, qi, ki, f, l, lv: (g, 0, qi[s])),
                ],
                scratch_shapes=[
                    pltpu.VMEM((nh * block_q, block_k), jnp.float32),
                    pltpu.VMEM((nh * block_q, 128), jnp.float32),
                    pltpu.VMEM((nh * block_q, 128), jnp.float32),
                    pltpu.VMEM((nh * block_q, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((h, 1, tp), jnp.float32),
            ],
            cost_estimate=_cost_estimate(
                flops=4 * h * n_flat * block_q * block_k * d,
                transcendentals=h * n_flat * block_q * block_k,
                bytes_accessed=(h * n_flat * (block_q + 2 * block_k) * d
                                * it + h * tp * d * it),
                name="varlen.fwd_stacked"),
            interpret=_interpret(),
        )(qi_a, ki_a, first_a, last_a, live_a, qp, kp, vp, cq2d, ck2d)
    return o[:, :t], lse.reshape(h, tp)[:, :t]


# blocks for the stacked short-segment path. r5 re-sweep on the 16-seq
# 16k bench pack: 512x512 (nh drops 8->4 for VMEM) edges out 256x512
# (0.179 vs 0.173 eff); 384x512, 256x768, 512x768, 128x512 all worse.
STACKED_BLOCK_Q = 512
STACKED_BLOCK_K = 512


def _expand_codes(code, t):
    """[T] i32 -> (q-side [T, 128] lane-replicated,
                   kv-side [8, T] sublane-replicated), padded to t rows
    with PAD_CODE."""
    n = code.shape[0]
    if t != n:
        code = jnp.pad(code, (0, t - n), constant_values=PAD_CODE)
    qside = jax.lax.broadcast_in_dim(code, (t, 128), (0,))
    kvside = jax.lax.broadcast_in_dim(code, (8, t), (1,))
    return qside.astype(jnp.int32), kvside.astype(jnp.int32)


def _codes_from_cu(cu, total):
    """cu [B+1] i32 cumulative offsets -> packed [total] codes."""
    t = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu, t, side="right").astype(jnp.int32) - 1
    pos = t - cu[seg]
    return (seg << POS_BITS) | pos


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13))
def _flash_varlen(q, k, v, cu_q, cu_k, causal, scale, block_q, block_k,
                  self_attn, max_seqlen, n_flat_hint=None, stacked=False,
                  n_flat_bwd_hint=None):
    o, _ = _flash_varlen_fwd_impl(q, k, v, cu_q, cu_k, causal, scale,
                                  block_q, block_k, self_attn, max_seqlen,
                                  n_flat_hint, stacked)
    return o


def _inner_steps(n_full, block_rows, block_cols, max_seqlen):
    """Static bound on the live column-tile span of any row tile: the
    spanned segments cover at most block_rows + 2*max_seqlen columns
    (partial first/last segments extend beyond the tile's rows), i.e.
    that many cols / block_cols tiles plus alignment slack. Shrinking the
    inner grid to this removes the dead steps entirely — max_seqlen is
    the same STATIC int the reference's flash_attn_unpadded requires.

    SELF-ATTENTION ONLY: with distinct q/k packings a block_rows-row tile
    can span up to block_rows segments of up to max_seqlen columns EACH,
    so no useful static bound exists; callers must pass max_seqlen=None
    (enforced in the impl/bwd entry points)."""
    if not max_seqlen:
        return n_full
    return min(n_full, (block_rows + 2 * int(max_seqlen)) // block_cols + 3)


def _fwd_bounds(cu_q, cu_k, n_q, block_q, block_k, t, causal, self_attn):
    """Live k-tile [lo, hi] per q tile, with the causal diagonal folded in
    for self-attention packing."""
    lo, hi = _live_col_tiles(cu_q, cu_k, n_q, block_q, block_k, t)
    if causal and self_attn:
        # int32 throughout: the package runs with x64 on, and int64 scalar-
        # prefetch operands break Mosaic's SMEM lowering
        i = jnp.arange(n_q, dtype=jnp.int32)
        diag = ((i + 1) * block_q - 1) // block_k
        hi = jnp.minimum(hi, diag.astype(jnp.int32))
        hi = jnp.maximum(hi, lo)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _flash_varlen_fwd_impl(q, k, v, cu_q, cu_k, causal, scale, block_q,
                           block_k, self_attn, max_seqlen=None,
                           n_flat_hint=None, stacked=False):
    """q/k/v: [H, T, D] packed; cu_*: [B+1] i32 offsets. Returns (o, lse)."""
    if stacked and self_attn:
        return _flash_varlen_fwd_stacked(q, k, v, cu_q, causal, scale,
                                         STACKED_BLOCK_Q, STACKED_BLOCK_K,
                                         n_flat_hint)
    h, t, d = q.shape
    tk = k.shape[1]
    it = jnp.dtype(q.dtype).itemsize
    if not self_attn:
        max_seqlen = None  # the static span bound is unsound cross-attn
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, tk)
    qp, _ = _pad_rows(q, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    tp, tkp = qp.shape[1], kp.shape[1]
    code_q = _codes_from_cu(cu_q, t)
    code_k = code_q if self_attn and tk == t else _codes_from_cu(cu_k, tk)
    cq2d, _ = _expand_codes(code_q, tp)
    _, ck2d = _expand_codes(code_k, tkp)
    n_q, n_k = tp // block_q, tkp // block_k
    lo, hi = _fwd_bounds(cu_q, cu_k, n_q, block_q, block_k, t, causal,
                         self_attn)
    n_flat = n_q * _inner_steps(n_k, block_q, block_k, max_seqlen)
    if n_flat_hint is not None:
        # live-pair count measured by the wrapper while cu was still
        # concrete (cu is a tracer HERE — the custom_vjp boundary traces
        # its array args); the grid's ~1.3 µs fixed cost per step is what
        # dominates short-sequence packs, and the static bound is ~4x
        # over-provisioned for them
        n_flat = min(n_flat, n_flat_hint)
    qi_a, ki_a, first_a, last_a, live_a = _flat_schedule(lo, hi, n_q, n_flat)
    kernel = functools.partial(_fwd_kernel_varlen, causal=causal,
                               scale=scale)
    with _mosaic_ctx():
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=5,
                grid=(h, n_flat),
                in_specs=[
                    pl.BlockSpec((1, block_q, d),
                                 lambda b, s, qi, ki, f, l, lv: (b, qi[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, qi, ki, f, l, lv: (b, ki[s], 0)),
                    pl.BlockSpec((1, block_k, d),
                                 lambda b, s, qi, ki, f, l, lv: (b, ki[s], 0)),
                    pl.BlockSpec((block_q, 128),
                                 lambda b, s, qi, ki, f, l, lv: (qi[s], 0)),
                    pl.BlockSpec((8, block_k),
                                 lambda b, s, qi, ki, f, l, lv: (0, ki[s])),
                ],
                out_specs=[
                    pl.BlockSpec((1, block_q, d),
                                 lambda b, s, qi, ki, f, l, lv: (b, qi[s], 0)),
                    pl.BlockSpec((1, 1, block_q),
                                 lambda b, s, qi, ki, f, l, lv: (b, 0, qi[s])),
                ],
                scratch_shapes=[
                    pltpu.VMEM((block_q, 128), jnp.float32),
                    pltpu.VMEM((block_q, 128), jnp.float32),
                    pltpu.VMEM((block_q, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((h, 1, tp), jnp.float32),
            ],
            cost_estimate=_cost_estimate(
                flops=4 * h * n_flat * block_q * block_k * d,
                transcendentals=h * n_flat * block_q * block_k,
                bytes_accessed=(h * n_flat * (block_q + 2 * block_k) * d
                                * it + h * tp * d * it),
                name="varlen.fwd"),
            interpret=_interpret(),
        )(qi_a, ki_a, first_a, last_a, live_a, qp, kp, vp, cq2d, ck2d)
    return o[:, :t], lse.reshape(h, tp)[:, :t]


def _flash_varlen_fwd(q, k, v, cu_q, cu_k, causal, scale, block_q,
                      block_k, self_attn, max_seqlen, n_flat_hint=None,
                      stacked=False, n_flat_bwd_hint=None):
    o, lse = _flash_varlen_fwd_impl(q, k, v, cu_q, cu_k, causal, scale,
                                    block_q, block_k, self_attn, max_seqlen,
                                    n_flat_hint, stacked)
    return o, (q, k, v, cu_q, cu_k, o, lse)


def _bwd_fused_call(qp, kp, vp, dop, lse3, delta3, cq2d, ck2d, ki_a, qi_a,
                    first_a, last_a, live_a, n_flat, nh, block_q, block_k,
                    causal, scale):
    """pallas_call plumbing for _bwd_fused_kernel_varlen: grid
    (H/nh, n_flat), five scalar-prefetched schedule arrays feeding every
    index map, nh heads per block."""
    h, tp, d = qp.shape
    tkp = kp.shape[1]
    it = jnp.dtype(qp.dtype).itemsize
    kernel = functools.partial(_bwd_fused_kernel_varlen, causal=causal,
                               scale=scale, nh=nh, block_q=block_q,
                               block_k=block_k, tp=tp)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(h // nh, n_flat),
            in_specs=[
                pl.BlockSpec((nh, block_q, d),
                             lambda g, s, ki, qi, f, l, lv: (g, qi[s], 0)),
                pl.BlockSpec((nh, block_k, d),
                             lambda g, s, ki, qi, f, l, lv: (g, ki[s], 0)),
                pl.BlockSpec((nh, block_k, d),
                             lambda g, s, ki, qi, f, l, lv: (g, ki[s], 0)),
                pl.BlockSpec((nh, block_q, d),
                             lambda g, s, ki, qi, f, l, lv: (g, qi[s], 0)),
                pl.BlockSpec((nh, 1, block_q),
                             lambda g, s, ki, qi, f, l, lv: (g, 0, qi[s])),
                pl.BlockSpec((nh, 1, block_q),
                             lambda g, s, ki, qi, f, l, lv: (g, 0, qi[s])),
                pl.BlockSpec((block_q, 128),
                             lambda g, s, ki, qi, f, l, lv: (qi[s], 0)),
                pl.BlockSpec((8, block_k),
                             lambda g, s, ki, qi, f, l, lv: (0, ki[s])),
            ],
            out_specs=[
                pl.BlockSpec((nh, block_q, d),
                             lambda g, s, ki, qi, f, l, lv: (g, qi[s], 0)),
                pl.BlockSpec((nh, block_k, d),
                             lambda g, s, ki, qi, f, l, lv: (g, ki[s], 0)),
                pl.BlockSpec((nh, block_k, d),
                             lambda g, s, ki, qi, f, l, lv: (g, ki[s], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((nh * block_k, d), jnp.float32),
                pltpu.VMEM((nh * block_k, d), jnp.float32),
                pltpu.VMEM((nh * tp, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, qp.dtype),
            jax.ShapeDtypeStruct(kp.shape, kp.dtype),
            jax.ShapeDtypeStruct(vp.shape, vp.dtype),
        ],
        compiler_params=_tpu_compiler_params(
            vmem_limit_bytes=_BWD_VMEM_LIMIT),
        cost_estimate=_cost_estimate(
            flops=10 * h * n_flat * block_q * block_k * d,
            transcendentals=h * n_flat * block_q * block_k,
            bytes_accessed=(2 * h * n_flat * (block_q + block_k) * d * it
                            + h * (tp + 2 * tkp) * d * it),
            name="varlen.bwd_fused"),
        interpret=_interpret(),
    )(ki_a, qi_a, first_a, last_a, live_a, qp, kp, vp, dop, lse3, delta3,
      cq2d, ck2d)


def _flash_varlen_bwd(causal, scale, block_q, block_k, self_attn,
                      max_seqlen, n_flat_hint, stacked, n_flat_bwd_hint,
                      res, do):
    """Flat-scheduled varlen backward: one k-major live-tile schedule
    drives a FUSED dK/dV/dQ kernel when the persistent dQ scratch fits
    VMEM (_bwd_fused_nh), else the split flat kernels (dK/dV k-major,
    dQ on the forward's q-major schedule). Either way every grid step is
    a live (q-tile, k-tile) pair — the old rectangular (H, n_k, n_q) /
    (H, n_q, n_k) grids burned a fixed-cost predicated step on every
    dead tile, which dominated short-segment packs ~30:1."""
    q, k, v, cu_q, cu_k, o, lse = res
    h, t, d = q.shape
    tk = k.shape[1]
    if not self_attn:
        max_seqlen = None  # see _inner_steps: bound unsound cross-attn
    if stacked and self_attn:
        # the stacked forward ran at the stacked tiling; keep the
        # backward on the same blocks so short-segment packs get the
        # same quadratic dead-area savings (1024^2 tiles on 512-token
        # segments are 75% dead even inside live tiles)
        block_q, block_k = STACKED_BLOCK_Q, STACKED_BLOCK_K
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, tk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp, _ = _pad_rows(q, block_q)
    dop, _ = _pad_rows(do, block_q)
    kp, _ = _pad_rows(k, block_k)
    vp, _ = _pad_rows(v, block_k)
    tp, tkp = qp.shape[1], kp.shape[1]
    lse3, _ = _pad_rows(lse.reshape(h, t, 1), block_q)
    delta3, _ = _pad_rows(delta.reshape(h, t, 1), block_q)
    lse3 = lse3.reshape(h, 1, tp)
    delta3 = delta3.reshape(h, 1, tp)
    code_q = _codes_from_cu(cu_q, t)
    code_k = code_q if self_attn and tk == t else _codes_from_cu(cu_k, tk)
    cq2d, _ = _expand_codes(code_q, tp)
    _, ck2d = _expand_codes(code_k, tkp)
    n_q, n_k = tp // block_q, tkp // block_k
    it = jnp.dtype(q.dtype).itemsize

    # k-major live-tile schedule (dK/dV accumulation order); same static
    # bound + concrete-cu hint scheme as the forward grid
    lo_q, hi_q = _bwd_bounds(cu_q, cu_k, n_k, block_q, block_k, tk,
                             causal, self_attn)
    n_flat = n_k * _inner_steps(n_q, block_k, block_q, max_seqlen)
    if n_flat_bwd_hint is not None:
        n_flat = min(n_flat, n_flat_bwd_hint)
    ki_a, qi_a, first_a, last_a, live_a = _flat_schedule(lo_q, hi_q, n_k,
                                                         n_flat)
    nh = _bwd_fused_nh(h, it, d, block_q, block_k, tp)
    with _mosaic_ctx():
        if nh:
            dq, dk, dv = _bwd_fused_call(
                qp, kp, vp, dop, lse3, delta3, cq2d, ck2d, ki_a, qi_a,
                first_a, last_a, live_a, n_flat, nh, block_q, block_k,
                causal, scale)
            if not self_attn:
                # k-major presentation only reaches q tiles inside some
                # k tile's live range; a cross-attn pack can LEAD/TRAIL
                # with q segments that have zero k tokens, whose dq HBM
                # blocks are then never written. Their true gradient is
                # zero (no keys -> masked-to-zero output), so zero any
                # uncovered tile in-graph. Self-attention needs no fix:
                # its k-major live set is the transpose of the forward's
                # q-major set, which presents every q tile.
                i = jnp.arange(n_q, dtype=jnp.int32)
                cover = jnp.any((i[None, :] >= lo_q[:, None])
                                & (i[None, :] <= hi_q[:, None]), axis=0)
                dq = jnp.where(jnp.repeat(cover, block_q)[None, :, None],
                               dq, jnp.zeros((), dq.dtype)).astype(qp.dtype)
        else:
            dk, dv = pl.pallas_call(
                functools.partial(_bwd_dkv_flat_kernel, causal=causal,
                                  scale=scale),
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=5,
                    grid=(h, n_flat),
                    in_specs=[
                        pl.BlockSpec(
                            (1, block_q, d),
                            lambda b, s, ki, qi, f, l, lv: (b, qi[s], 0)),
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, ki, qi, f, l, lv: (b, ki[s], 0)),
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, ki, qi, f, l, lv: (b, ki[s], 0)),
                        pl.BlockSpec(
                            (1, block_q, d),
                            lambda b, s, ki, qi, f, l, lv: (b, qi[s], 0)),
                        pl.BlockSpec(
                            (1, 1, block_q),
                            lambda b, s, ki, qi, f, l, lv: (b, 0, qi[s])),
                        pl.BlockSpec(
                            (1, 1, block_q),
                            lambda b, s, ki, qi, f, l, lv: (b, 0, qi[s])),
                        pl.BlockSpec(
                            (block_q, 128),
                            lambda b, s, ki, qi, f, l, lv: (qi[s], 0)),
                        pl.BlockSpec(
                            (8, block_k),
                            lambda b, s, ki, qi, f, l, lv: (0, ki[s])),
                    ],
                    out_specs=[
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, ki, qi, f, l, lv: (b, ki[s], 0)),
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, ki, qi, f, l, lv: (b, ki[s], 0)),
                    ],
                    scratch_shapes=[
                        pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32),
                    ],
                ),
                out_shape=[
                    jax.ShapeDtypeStruct(kp.shape, k.dtype),
                    jax.ShapeDtypeStruct(vp.shape, v.dtype),
                ],
                cost_estimate=_cost_estimate(
                    flops=8 * h * n_flat * block_q * block_k * d,
                    transcendentals=h * n_flat * block_q * block_k,
                    bytes_accessed=(2 * h * n_flat * (block_q + block_k)
                                    * d * it + 2 * h * tkp * d * it),
                    name="varlen.bwd_dkv"),
                interpret=_interpret(),
            )(ki_a, qi_a, first_a, last_a, live_a, qp, kp, vp, dop, lse3,
              delta3, cq2d, ck2d)

            # dQ rides the forward's q-major schedule (same bounds, same
            # hint): every q tile is presented, so no coverage fix needed
            lo_k, hi_k = _fwd_bounds(cu_q, cu_k, n_q, block_q, block_k, t,
                                     causal, self_attn)
            n_flat_q = n_q * _inner_steps(n_k, block_q, block_k,
                                          max_seqlen)
            if n_flat_hint is not None:
                n_flat_q = min(n_flat_q, n_flat_hint)
            qi_b, ki_b, first_b, last_b, live_b = _flat_schedule(
                lo_k, hi_k, n_q, n_flat_q)
            dq = pl.pallas_call(
                functools.partial(_bwd_dq_flat_kernel, causal=causal,
                                  scale=scale),
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=5,
                    grid=(h, n_flat_q),
                    in_specs=[
                        pl.BlockSpec(
                            (1, block_q, d),
                            lambda b, s, qi, ki, f, l, lv: (b, qi[s], 0)),
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, qi, ki, f, l, lv: (b, ki[s], 0)),
                        pl.BlockSpec(
                            (1, block_k, d),
                            lambda b, s, qi, ki, f, l, lv: (b, ki[s], 0)),
                        pl.BlockSpec(
                            (1, block_q, d),
                            lambda b, s, qi, ki, f, l, lv: (b, qi[s], 0)),
                        pl.BlockSpec(
                            (1, 1, block_q),
                            lambda b, s, qi, ki, f, l, lv: (b, 0, qi[s])),
                        pl.BlockSpec(
                            (1, 1, block_q),
                            lambda b, s, qi, ki, f, l, lv: (b, 0, qi[s])),
                        pl.BlockSpec(
                            (block_q, 128),
                            lambda b, s, qi, ki, f, l, lv: (qi[s], 0)),
                        pl.BlockSpec(
                            (8, block_k),
                            lambda b, s, qi, ki, f, l, lv: (0, ki[s])),
                    ],
                    out_specs=pl.BlockSpec(
                        (1, block_q, d),
                        lambda b, s, qi, ki, f, l, lv: (b, qi[s], 0)),
                    scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
                ),
                out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
                cost_estimate=_cost_estimate(
                    flops=6 * h * n_flat_q * block_q * block_k * d,
                    transcendentals=h * n_flat_q * block_q * block_k,
                    bytes_accessed=(2 * h * n_flat_q * (block_q + block_k)
                                    * d * it + h * tp * d * it),
                    name="varlen.bwd_dq"),
                interpret=_interpret(),
            )(qi_b, ki_b, first_b, last_b, live_b, qp, kp, vp, dop, lse3,
              delta3, cq2d, ck2d)
    return dq[:, :t], dk[:, :tk], dv[:, :tk], None, None


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


def _host_bounds(cu_rows, cu_cols, n_tiles, block_rows, block_cols,
                 total_rows):
    """Pure-NUMPY mirror of _live_col_tiles: jnp ops issued during an
    enclosing trace are staged even on concrete inputs, so the wrapper's
    schedule sizing must not touch jnp."""
    import numpy as np
    i = np.arange(n_tiles)
    r0 = np.clip(i * block_rows, 0, total_rows - 1)
    r1 = np.clip((i + 1) * block_rows - 1, 0, total_rows - 1)
    seg0 = np.searchsorted(cu_rows, r0, side="right") - 1
    seg1 = np.searchsorted(cu_rows, r1, side="right") - 1
    lo = cu_cols[seg0] // block_cols
    hi = (np.maximum(cu_cols[seg1 + 1], cu_cols[seg1] + 1) - 1) // block_cols
    return lo, np.maximum(hi, lo)


def _host_schedule(cuq_np, cuk_np, tq, tk, bq, bk, causal, self_attn):
    """Live (q-tile, k-tile) pair counts for BOTH flat-grid orientations
    at a concrete cu: q-major (forward / split dQ, _fwd_bounds' causal
    diagonal clamp) and k-major (backward dK/dV + fused kernel,
    _bwd_bounds' diagonal start). Returns
    (n_live_fwd, n_live_bwd, n_q, n_k)."""
    import numpy as np
    n_q = -(-tq // bq)
    n_k = -(-tk // bk)
    lo, hi = _host_bounds(cuq_np, cuk_np, n_q, bq, bk, tq)
    if causal and self_attn:
        i = np.arange(n_q)
        hi = np.maximum(np.minimum(hi, ((i + 1) * bq - 1) // bk), lo)
    n_live_fwd = int(np.sum(hi - lo + 1))
    lo2, hi2 = _host_bounds(cuk_np, cuq_np, n_k, bk, bq, tk)
    if causal and self_attn:
        j = np.arange(n_k)
        lo2 = np.maximum(lo2, (j * bk) // bq)
        hi2 = np.maximum(hi2, lo2)
    n_live_bwd = int(np.sum(hi2 - lo2 + 1))
    return n_live_fwd, n_live_bwd, n_q, n_k


def _pow2_hint(n_live):
    """Flat-grid length for a measured live-pair count: next power of two
    (>= 8) so repacked batches of similar size reuse compiled programs."""
    h = 8
    while h < n_live:
        h *= 2
    return h


def _host_plan(cuq_np, cuk_np, tq, tk, h, d, itemsize, causal, self_attn,
               block_q, block_k, max_seqlen=None):
    """Concrete-cu kernel plan: stacked-path selection, fitted blocks,
    and per-orientation schedule sizes. `flat` is the grid the flat
    schedule actually runs (live count pow2-rounded, capped by the
    static bound); `rect` is what the old rectangular grid would have
    burned — the gap is all dead steps.

    Short-segment packs (mean segment < 1024 tokens) at the DEFAULT
    blocks go to the rows-stacked head-fused tiling: small tiles cut the
    dead-area waste of 1024^2 tiles quadratically, and stacking pays the
    serial softmax-chain latency once per chunk instead of once per
    (chunk, head). The stacked kernel must also FIT scoped VMEM at this
    dtype (f32 doubles the block bytes — advisor r4: nh=8 f32 was a
    compile-time OOM) and needs >= 2 fused heads to amortize anything.
    Callers passing EXPLICIT block sizes keep the streaming kernel with
    exactly those blocks (tuning stays honored)."""
    stacked = False
    if self_attn and len(cuq_np) > 1 \
            and (block_q, block_k) == (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K):
        mean_seg = tq / (len(cuq_np) - 1)
        nh_fit = _stacked_nh(h, itemsize, d,
                             _fit_block(STACKED_BLOCK_Q, tq),
                             _fit_block(STACKED_BLOCK_K, tk))
        stacked = bool(mean_seg < 1024) and nh_fit >= 2
    if stacked:
        bq = _fit_block(STACKED_BLOCK_Q, tq)
        bk = _fit_block(STACKED_BLOCK_K, tk)
    else:
        bq, bk = _fit_block(block_q, tq), _fit_block(block_k, tk)
    live_fwd, live_bwd, n_q, n_k = _host_schedule(
        cuq_np, cuk_np, tq, tk, bq, bk, causal, self_attn)
    if not self_attn:
        max_seqlen = None  # see _inner_steps
    rect_fwd = n_q * _inner_steps(n_k, bq, bk, max_seqlen)
    rect_bwd = n_k * _inner_steps(n_q, bk, bq, max_seqlen)
    return {
        "stacked": stacked,
        "block_q": int(bq),
        "block_k": int(bk),
        "fwd": {"live": live_fwd, "rect": int(rect_fwd),
                "flat": int(min(_pow2_hint(live_fwd), rect_fwd)),
                "flat_hint": _pow2_hint(live_fwd)},
        "bwd": {"live": live_bwd, "rect": int(rect_bwd),
                "flat": int(min(_pow2_hint(live_bwd), rect_bwd)),
                "flat_hint": _pow2_hint(live_bwd)},
    }


def varlen_schedule_stats(cu_q, cu_k, heads, head_dim, *, causal,
                          self_attn=True, dtype=jnp.bfloat16,
                          block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                          max_seqlen=None):
    """Dead-vs-live grid-step accounting for a concrete pack: what the
    flat live-tile schedule runs vs what the rectangular grids burned.
    All values are plain ints/bools (JSON-ready — bench.py records this
    in BENCH_DETAIL.json)."""
    import numpy as np
    cuq_np = np.asarray(cu_q)  # noqa: PTA006 -- bench/telemetry helper on concrete cu, outside any step
    cuk_np = cuq_np if self_attn else np.asarray(cu_k)  # noqa: PTA006 -- bench/telemetry helper on concrete cu, outside any step
    tq, tk = int(cuq_np[-1]), int(cuk_np[-1])
    plan = _host_plan(cuq_np, cuk_np, tq, tk, heads, head_dim,
                      jnp.dtype(dtype).itemsize, causal, self_attn,
                      block_q, block_k,
                      int(max_seqlen) if max_seqlen else None)
    out = {"stacked": bool(plan["stacked"]),
           "block_q": plan["block_q"], "block_k": plan["block_k"]}
    for pss in ("fwd", "bwd"):
        p = plan[pss]
        out[pss] = {"live_tiles": p["live"],
                    "flat_steps": p["flat"],
                    "rect_steps": p["rect"],
                    "dead_steps_flat": p["flat"] - p["live"],
                    "dead_steps_rect": p["rect"] - p["live"]}
    return out


def flash_varlen_attention(q, k, v, cu_seqlens_q, cu_seqlens_k, scale,
                           causal, self_attn=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                           max_seqlen=None):
    """Kernel-backed packed varlen attention.

    q: [total_q, H, D]; k/v: [total_k, Hkv, D] (GQA repeats kv heads);
    cu_seqlens_*: [B+1] i32 cumulative offsets. Returns [total_q, H, D].
    self_attn=True (auto-detected from object identity of the cu arrays)
    additionally skips DMA/compute of above-diagonal tiles under causal.
    """
    if self_attn is None:
        self_attn = cu_seqlens_q is cu_seqlens_k
    tq, h, d = q.shape
    tk = k.shape[0]
    hkv = k.shape[1]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    cu_q = cu_seqlens_q.astype(jnp.int32)
    cu_k = cu_q if self_attn else cu_seqlens_k.astype(jnp.int32)
    if max_seqlen and self_attn:
        # a lying max_seqlen silently shrinks the live-tile span bound
        # (_inner_steps) below real segments → wrong output. Validate on
        # the host when cu is concrete (the common eager path); under a
        # trace fall back to the always-sound full inner grid. Cross-attn
        # already ignores max_seqlen (span bound unsound there).
        import jax.core as _jc
        concrete = not isinstance(cu_q, _jc.Tracer)
        if concrete:
            import numpy as _np
            longest = int(_np.max(_np.diff(_np.asarray(cu_q))))  # noqa: PTA006 -- guarded to concrete (non-tracer) cu only
            if longest > int(max_seqlen):
                raise ValueError(
                    f"flash_varlen_attention: max_seqlen={int(max_seqlen)} "
                    f"is smaller than the longest packed segment "
                    f"({longest}); the static live-tile bound would skip "
                    f"live tiles and produce wrong attention output")
        else:
            max_seqlen = None
    n_flat_hint = None
    n_flat_bwd_hint = None
    stacked = False
    if not isinstance(cu_q, jax.core.Tracer) \
            and not isinstance(cu_k, jax.core.Tracer):
        # cu concrete here (it becomes a tracer at the custom_vjp
        # boundary): measure the actual live-pair counts so BOTH flat
        # grids (forward q-major, backward k-major) are sized to the
        # work, not the worst-case static bound — the grid's ~1.3 µs
        # fixed cost per step is what dominates short-sequence packs,
        # and the static bound is ~4x over-provisioned for them.
        import numpy as np
        plan = _host_plan(np.asarray(cu_q), np.asarray(cu_k), tq, tk, h, d,  # noqa: PTA006 -- flat schedule is planned on host from concrete cu
                          jnp.dtype(q.dtype).itemsize, causal,
                          bool(self_attn), block_q, block_k,
                          int(max_seqlen) if max_seqlen else None)
        stacked = plan["stacked"]
        n_flat_hint = plan["fwd"]["flat_hint"]
        n_flat_bwd_hint = plan["bwd"]["flat_hint"]
    qh = q.transpose(1, 0, 2)
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    o = _flash_varlen(qh, kh, vh, cu_q, cu_k, causal, float(scale),
                      block_q, block_k, bool(self_attn),
                      int(max_seqlen) if max_seqlen else None, n_flat_hint,
                      stacked, n_flat_bwd_hint)
    return o.transpose(1, 0, 2)
