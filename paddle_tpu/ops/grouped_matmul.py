"""Pallas grouped matmul (gmm) for dropless MoE expert compute.

Ref: the reference's capacity-bucketed expert matmuls
(incubate/distributed/models/moe) pad every expert to cf*T*k/E rows and
compute the padding — at cf=1.25 with 128-rounding that is ~25% dead MXU
work per MoE layer. MegaBlocks-style dropless replaces the buckets with
ONE ragged grouped GEMM over the expert-sorted token buffer:

    out[rows of group e] = lhs[rows of group e] @ rhs[e]

Group boundaries are TILE-ALIGNED by the caller (parallel/moe.py rounds
each expert's row count up to `tile_rows`), so every row tile belongs to
exactly one expert and the kernel runs one fixed grid of MXU row tiles,
reading the per-tile expert id / live / first / last flags out of SMEM
(scalar prefetch) — the same flat live-tile schedule planning the varlen
backward (ops/flash_varlen.py) uses. Padding is bounded by one row tile
per expert plus the tile-rounding of the total, NOT by a capacity
factor; tiles past the last live row skip their matmul entirely
(`pl.when(live)`), so dead-tail compute is a predicated no-op.

Three kernels, one schedule:
  _gmm_kernel      out  = lhs @ rhs[e]           grid (n_n, n_t), t minor
  _gmm_dx_kernel   dlhs = dout @ rhs[e].T        grid (n_k, n_t), t minor
  _gmm_dw_kernel   drhs[e] = sum_t lhs_t.T @ dout_t
                                                 grid (n_k, n_n, n_t)
t is the MINOR grid dim everywhere so consecutive steps walk tiles of
the same expert and Mosaic elides the rhs re-fetch (the block index is
unchanged); dW accumulates a group's tiles in VMEM scratch between its
first/last flags exactly like the varlen dKV accumulator.

The contraction dim is NOT split (full-K blocks): each grid step is one
dot, so no cross-step accumulator is needed in the forward/dX and the
per-row reduction order matches a plain XLA dot — the dropless MoE path
is BITWISE-equal to the dense einsum reference on CPU (test-asserted).
Block_n auto-shrinks until the rhs window fits the VMEM budget.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx

# default row tile: MXU-sized. Callers may shrink it for tiny tests.
TILE_ROWS = 128

# cap on one double-buffered rhs window (K x block_n): block_n halves
# until it fits so wide experts (K=4096) don't overrun scoped VMEM
_GMM_RHS_BUDGET = 8 * 1024 * 1024


def _round_up(n, m):
    return -(-n // m) * m


def _fit_block(dim, itemsize, k_rows, budget=_GMM_RHS_BUDGET):
    """Largest lane-dim block (<= dim, dividing dim, 128-min) whose
    double-buffered [k_rows, block] window fits the budget."""
    block = dim
    while block > 128 and 2 * k_rows * block * itemsize > budget:
        block //= 2
    while dim % block:
        block //= 2
    return max(block, 1)


def tile_schedule(counts, n_tiles, tile_rows=TILE_ROWS):
    """Per-tile flat schedule from per-expert row counts [E] (traced ok).

    Returns int32 arrays (tile_expert, live, first, last) of length
    ``n_tiles`` plus ``offsets`` [E+1] (tile-aligned row starts; the
    caller scatters pair rows to ``offsets[e] + queue_position``).
    Tiles past the last live row clamp their expert id to E-1 (same
    block re-presented -> rhs DMA elided) and carry live=0."""
    E = counts.shape[0]
    aligned = _round_up(counts, tile_rows)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(aligned).astype(jnp.int32)])          # [E+1]
    row0 = (jnp.arange(n_tiles, dtype=jnp.int32) * tile_rows)
    expert = jnp.clip(
        jnp.searchsorted(offsets, row0, side="right").astype(jnp.int32) - 1,
        0, E - 1)
    live = (row0 < offsets[E]).astype(jnp.int32)
    first = ((row0 == offsets[expert]) & (live == 1)).astype(jnp.int32)
    last = ((row0 + tile_rows == offsets[expert + 1])
            & (live == 1)).astype(jnp.int32)
    return (expert.astype(jnp.int32), live, first, last, offsets)


def chunk_schedule(counts, chunk_rows, tile_rows=TILE_ROWS):
    """Per-hop tile schedule for ONE ragged-a2a chunk (PR 10).

    ``counts`` [E_local] are the group sizes a single source rank packed
    into its ``chunk_rows``-row chunk with the same tile-aligned layout
    ``tile_schedule`` derives (cumsum of tile-rounded counts), so sender
    packing and receiver schedule agree by construction. Returns the
    4-tuple ``(tile_expert, live, first, last)`` ``grouped_matmul``
    consumes — one schedule per arrived chunk is what lets expert FFN
    start on hop h's rows while hop h+1's ppermute is still in flight."""
    assert chunk_rows % tile_rows == 0, (chunk_rows, tile_rows)
    return tile_schedule(counts, chunk_rows // tile_rows, tile_rows)[:4]


def _gmm_kernel(e_ref, lv_ref, f_ref, l_ref, x_ref, w_ref, o_ref, *,
                out_dtype):
    t = pl.program_id(1)

    @pl.when(lv_ref[t] == 1)
    def _dot():
        o_ref[...] = jax.lax.dot_general(
            x_ref[...], w_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_dtype)

    @pl.when(lv_ref[t] == 0)
    def _dead():
        # dead-tail rows are never gathered by the combine, but leaving
        # the block uninitialized would leak garbage into buffer-level
        # consumers (tests, debugging dumps): zero them
        o_ref[...] = jnp.zeros_like(o_ref)


def _gmm_dx_kernel(e_ref, lv_ref, f_ref, l_ref, g_ref, w_ref, o_ref, *,
                   out_dtype):
    t = pl.program_id(1)

    @pl.when(lv_ref[t] == 1)
    def _dot():
        # dx_tile = dout_tile [tm, N] @ rhs[e][kblk, N].T
        o_ref[...] = jax.lax.dot_general(
            g_ref[...], w_ref[0],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_dtype)

    @pl.when(lv_ref[t] == 0)
    def _dead():
        o_ref[...] = jnp.zeros_like(o_ref)


def _gmm_dw_kernel(e_ref, lv_ref, f_ref, l_ref, x_ref, g_ref, o_ref,
                   acc_s, *, out_dtype):
    t = pl.program_id(2)

    @pl.when(f_ref[t] == 1)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(lv_ref[t] == 1)
    def _dot():
        acc_s[...] = acc_s[...] + jax.lax.dot_general(
            x_ref[...], g_ref[...],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(l_ref[t] == 1)
    def _flush():
        o_ref[0] = acc_s[...].astype(out_dtype)


def _sched_i32(sched):
    expert, live, first, last = sched
    return (jnp.asarray(expert, jnp.int32), jnp.asarray(live, jnp.int32),
            jnp.asarray(first, jnp.int32), jnp.asarray(last, jnp.int32))


def _gmm_fwd_call(lhs, rhs, sched, tile_rows):
    m, k = lhs.shape
    E, _, n = rhs.shape
    n_t = m // tile_rows
    out_dtype = jnp.promote_types(lhs.dtype, rhs.dtype)
    block_n = _fit_block(n, jnp.dtype(rhs.dtype).itemsize, k)
    it = jnp.dtype(lhs.dtype).itemsize
    kernel = functools.partial(_gmm_kernel, out_dtype=out_dtype)
    with _mosaic_ctx():
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(n // block_n, n_t),
                in_specs=[
                    pl.BlockSpec((tile_rows, k),
                                 lambda nb, t, e, lv, f, l: (t, 0)),
                    pl.BlockSpec((1, k, block_n),
                                 lambda nb, t, e, lv, f, l: (e[t], 0, nb)),
                ],
                out_specs=pl.BlockSpec(
                    (tile_rows, block_n),
                    lambda nb, t, e, lv, f, l: (t, nb)),
            ),
            out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
            cost_estimate=_cost_estimate(
                flops=2 * m * k * n,
                bytes_accessed=(m * k + E * k * n) * it
                + m * n * jnp.dtype(out_dtype).itemsize,
                name="gmm.fwd"),
            interpret=_interpret(),
        )(*_sched_i32(sched), lhs, rhs)


def _gmm_dx_call(dout, rhs, sched, tile_rows, dx_dtype):
    m, n = dout.shape
    E, k, _ = rhs.shape
    n_t = m // tile_rows
    block_k = _fit_block(k, jnp.dtype(rhs.dtype).itemsize, n)
    it = jnp.dtype(dout.dtype).itemsize
    kernel = functools.partial(_gmm_dx_kernel, out_dtype=dx_dtype)
    with _mosaic_ctx():
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(k // block_k, n_t),
                in_specs=[
                    pl.BlockSpec((tile_rows, n),
                                 lambda kb, t, e, lv, f, l: (t, 0)),
                    pl.BlockSpec((1, block_k, n),
                                 lambda kb, t, e, lv, f, l: (e[t], kb, 0)),
                ],
                out_specs=pl.BlockSpec(
                    (tile_rows, block_k),
                    lambda kb, t, e, lv, f, l: (t, kb)),
            ),
            out_shape=jax.ShapeDtypeStruct((m, k), dx_dtype),
            cost_estimate=_cost_estimate(
                flops=2 * m * k * n,
                bytes_accessed=(m * n + E * k * n) * it
                + m * k * jnp.dtype(dx_dtype).itemsize,
                name="gmm.dx"),
            interpret=_interpret(),
        )(*_sched_i32(sched), dout, rhs)


def _gmm_dw_call(lhs, dout, sched, tile_rows, E, dw_dtype):
    m, k = lhs.shape
    n = dout.shape[1]
    n_t = m // tile_rows
    it = jnp.dtype(lhs.dtype).itemsize
    # acc scratch is [block_k, block_n] f32: shrink block_k, then
    # block_n, until the accumulator fits the budget (each extra k/n
    # block re-streams the whole token buffer, so prefer big blocks)
    budget = 2 * _GMM_RHS_BUDGET
    block_k, block_n = k, n
    while block_k > 128 and block_k * block_n * 4 > budget:
        block_k //= 2
    while block_n > 128 and block_k * block_n * 4 > budget:
        block_n //= 2
    while k % block_k:
        block_k //= 2
    while n % block_n:
        block_n //= 2
    kernel = functools.partial(_gmm_dw_kernel, out_dtype=dw_dtype)
    with _mosaic_ctx():
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(k // block_k, n // block_n, n_t),
                in_specs=[
                    pl.BlockSpec((tile_rows, block_k),
                                 lambda kb, nb, t, e, lv, f, l: (t, kb)),
                    pl.BlockSpec((tile_rows, block_n),
                                 lambda kb, nb, t, e, lv, f, l: (t, nb)),
                ],
                out_specs=pl.BlockSpec(
                    (1, block_k, block_n),
                    lambda kb, nb, t, e, lv, f, l: (e[t], kb, nb)),
                scratch_shapes=[
                    pltpu.VMEM((block_k, block_n), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((E, k, n), dw_dtype),
            cost_estimate=_cost_estimate(
                flops=2 * m * k * n,
                bytes_accessed=m * (k + n) * it
                + E * k * n * jnp.dtype(dw_dtype).itemsize,
                name="gmm.dw"),
            interpret=_interpret(),
        )(*_sched_i32(sched), lhs, dout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def grouped_matmul(lhs, rhs, sched, tile_rows=TILE_ROWS):
    """Ragged grouped GEMM: rows of ``lhs`` [M, K] in group e multiply
    ``rhs`` [E, K, N] -> out [M, N].

    ``sched`` = (tile_expert, live, first, last), int32 [M//tile_rows]
    arrays from ``tile_schedule`` — group boundaries must be aligned to
    ``tile_rows`` (the moe dispatch guarantees this) and M must be a
    multiple of ``tile_rows``. Rows past the last live tile come back
    zero. Differentiable in lhs and rhs (dX/dW run the same flat tile
    schedule); the schedule arrays get no gradient."""
    assert lhs.shape[0] % tile_rows == 0, (lhs.shape, tile_rows)
    return _gmm_fwd_call(lhs, rhs, sched, tile_rows)


def _grouped_matmul_fwd(lhs, rhs, sched, tile_rows):
    return grouped_matmul(lhs, rhs, sched, tile_rows), (lhs, rhs, sched)


def _grouped_matmul_bwd(tile_rows, res, g):
    lhs, rhs, sched = res
    E = rhs.shape[0]
    dlhs = _gmm_dx_call(g, rhs, sched, tile_rows, lhs.dtype)
    dw = _gmm_dw_call(lhs, g, sched, tile_rows, E, jnp.float32)
    # empty groups have no tiles -> their dW block is never presented to
    # the kernel and holds uninitialized memory: select (not multiply —
    # garbage could be NaN) zeros for them. `first` fires exactly once
    # per non-empty group.
    expert, live, first, last = sched
    has_rows = jnp.zeros((E,), jnp.int32).at[expert].add(first)
    dw = jnp.where(has_rows[:, None, None] > 0, dw,
                   jnp.zeros_like(dw)).astype(rhs.dtype)
    return dlhs, dw, None


grouped_matmul.defvjp(_grouped_matmul_fwd, _grouped_matmul_bwd)
