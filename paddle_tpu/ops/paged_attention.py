"""Ragged paged decode attention over a block-table KV pool.

The serving engine (paddle_tpu/inference/) keeps the KV cache as a pool
of fixed-size blocks [L, NP, KVD, block_size] plus per-sequence int32
block tables — the vLLM PagedAttention layout (Kwon et al., SOSP '23)
restated for TPU static shapes. The kernel walks a FLAT schedule of
live (sequence, block) pairs built host/trace-side with the same
cumsum + searchsorted group-boundary trick as grouped_matmul's
tile_schedule: dead table slots are never stepped, dead grid steps
re-present the last live block index so Mosaic elides their DMA, and
all per-step bounds arrive via SMEM scalar prefetch.

Numerics contract (see PARITY.md): the kernel runs the EXACT op
sequence of decode_attention._kernel per sequence — tile-0-anchored
exp2 softmax (or the PADDLE_TPU_FLASH_SOFTMAX=online recurrence),
q PRE-SCALED by scale*log2(e), finalize acc / max(l, 1e-30) — so at
B=1 with block_size == the slab kernel's T tile (128) the output is
BITWISE-equal to decode_attention_slab on a contiguous layout, and a
fragmented block table is bitwise-equal to a contiguous one at any
batch (the schedule changes only WHERE a block lives, never the op
order).

paged_attend_update fuses the new token's KV write into the walk (the
pool aliases through the custom call, mirroring
decode_attend_update_slab): the schedule is built over len+1 positions
so the newest block is always the sequence's last live tile, the new
column is merged there, and that step's scores read the just-written
tile back from the aliased out refs.

Layouts:
  q_bd    [B, NH, KVD]          pre-scaled block-diagonal queries
  pools   [L, NP, KVD, bs]      k and v block pools, time in lanes
  tables  [B, max_nb] int32     pool block ids per sequence slot
  lengths [B] int32             live tokens (read path) / positions [B]
                                of the NEW token (update path)
Block 0 of the pool is reserved as a null block by the engine: padding
rows point every table slot at it, so their (masked) garbage never
lands in a live block.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx
from .flash_attention import softmax_mode

_LOG2E = 1.4426950408889634

# sched row indices (one [N_FIELDS, n_steps] i32 scalar-prefetch array)
_SEQ, _BLK, _START, _FIRST, _LAST, _LIVE, _POS, _COL, _UBLK = range(9)
N_FIELDS = 9


def paged_schedule(lengths, tables, n_steps, block_size):
    """Flat live-block schedule: [N_FIELDS, n_steps] i32.

    lengths [B] live token counts (a 0 row is skipped entirely),
    tables [B, max_nb]. Walks sequence s's ceil(lengths[s]/block_size)
    blocks in table order; steps past the live total repeat the LAST
    live step's (seq, blk) so their block windows re-present unchanged
    indices and Mosaic skips the copy — the grouped_matmul
    tile_schedule trick, keyed by sequence instead of expert. Works on
    traced values (pure jnp)."""
    B, max_nb = tables.shape
    bs = jnp.int32(block_size)
    lens = jnp.maximum(lengths.astype(jnp.int32), 0)
    counts = (lens + bs - 1) // bs
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(counts).astype(jnp.int32)])
    total = offsets[-1]
    step = jnp.arange(n_steps, dtype=jnp.int32)
    # clamp flat index so dead steps REPLAY the final live step exactly
    fs = jnp.minimum(step, jnp.maximum(total - 1, 0))
    seq = jnp.clip(jnp.searchsorted(offsets, fs, side="right") - 1,
                   0, B - 1).astype(jnp.int32)
    inner = fs - offsets[seq]
    blk = tables[seq, jnp.clip(inner, 0, max_nb - 1)].astype(jnp.int32)
    live = (step < total).astype(jnp.int32)
    first = ((inner == 0) & (step < total)).astype(jnp.int32)
    last = ((fs == offsets[seq + 1] - 1) & (step < total)).astype(jnp.int32)
    pos = lens[seq] - 1
    last_slot = jnp.clip((lens[seq] - 1) // bs, 0, max_nb - 1)
    col = pos - ((lens[seq] - 1) // bs) * bs
    ublk = tables[seq, last_slot].astype(jnp.int32)
    return jnp.stack([seq, blk, inner * bs, first, last, live,
                      pos, col, ublk])


def paged_schedule_stats(lengths, tables, n_steps, block_size):
    """Host-side occupancy of a schedule: dict with live/dead step
    counts and the pool-block touch count (telemetry + bench)."""
    import numpy as np
    lens = np.maximum(np.asarray(lengths, np.int64), 0)  # noqa: PTA006 -- host-side schedule stats for telemetry, not a step path
    counts = (lens + block_size - 1) // block_size
    total = int(counts.sum())
    return {"n_steps": int(n_steps), "live_steps": min(total, int(n_steps)),
            "dead_steps": max(int(n_steps) - total, 0),
            "overflow_steps": max(total - int(n_steps), 0)}


def _paged_kernel(lp_ref, sc_ref, q_ref, k_ref, v_ref, o_ref,
                  l_s, b_s, acc_s, *, block_size, online=False):
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]

    def scores():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        return jax.lax.dot_general(
            p, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, KVD]

    @pl.when(sc_ref[_FIRST, j] == np.int32(1))
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p.astype(v_ref.dtype))

    @pl.when(jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1),
                             sc_ref[_FIRST, j] == np.int32(0)))
    def _more():
        s = scores()
        if online:
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p.astype(v_ref.dtype))
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p.astype(v_ref.dtype))

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        o_ref[0] = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))


def paged_attention(q_bd, k_pool, v_pool, tables, lengths, layer, *,
                    n_steps=None):
    """Read-only paged decode attention for one layer.

    q_bd [B, NH, KVD] PRE-SCALED by scale*log2(e); pools
    [L, NP, KVD, bs]; tables [B, max_nb] i32; lengths [B] i32 live
    token counts (every attended row must have lengths >= 1 — a 0 row
    is skipped and its output left unwritten). Returns [B, NH, KVD]
    f32. n_steps defaults to B * max_nb (the worst case); pass the
    engine's bucketed bound to shrink the grid."""
    b, nh, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    sched = paged_schedule(lengths, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    kernel = functools.partial(_paged_kernel, block_size=bs,
                               online=softmax_mode() == "online")
    with _mosaic_ctx():
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, nh, kvd), q_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                ],
                out_specs=pl.BlockSpec((1, nh, kvd), q_map),
                scratch_shapes=[
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, kvd), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
            cost_estimate=_cost_estimate(
                flops=4 * nh * kvd * bs * n_steps,
                transcendentals=nh * bs * n_steps,
                bytes_accessed=2 * kvd * bs * it * n_steps,
                name="paged.attention"),
            interpret=_interpret(),
        )(lp, sched, q_bd, k_pool, v_pool)
    return out


def _paged_update_kernel(lp_ref, sc_ref, q_ref, nk_ref, nv_ref,
                         k_ref, v_ref, o_ref, ko_ref, vo_ref,
                         l_s, b_s, acc_s, *, block_size, online=False):
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]
    col = sc_ref[_COL, j]
    first = sc_ref[_FIRST, j] == np.int32(1)
    upd = sc_ref[_LAST, j] == np.int32(1)   # the new token's block IS the last
    kvd = q_ref.shape[2]
    lane = lax.broadcasted_iota(jnp.int32, (kvd, block_size), 1)

    def merged(tile_ref, new_ref):
        # minor-dim insert goes through f32 (Mosaic bf16 limitation,
        # same as decode_attention._kernel_update)
        new32 = new_ref[0].astype(jnp.float32)[:, None]
        return jnp.where(lane == col, new32,
                         tile_ref[0, 0].astype(jnp.float32)) \
            .astype(tile_ref.dtype)

    @pl.when(upd)
    def _write_cache():
        # full tile written every update step: the aliased out window
        # starts uninitialized, so every lane must be defined before
        # the flush at the next sequence boundary
        ko_ref[0, 0] = merged(k_ref, nk_ref)
        vo_ref[0, 0] = merged(v_ref, nv_ref)

    def chain(k_at, v_at, is_first):
        s = jax.lax.dot_general(
            q_ref[0], k_at, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(t <= pos, s, jnp.float32(-1e30))
        alpha = None
        if is_first:
            bvec = s.max(axis=-1, keepdims=True)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        elif online:
            m_prev = b_s[:, :1]
            bvec = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - bvec)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        else:
            bvec = b_s[:, :1]
        p = jnp.exp2(s - bvec)
        psum = jnp.broadcast_to(p.sum(axis=-1, keepdims=True), l_s.shape)
        d = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_at, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if is_first:
            l_s[...] = psum
            acc_s[...] = d
        elif online:
            l_s[...] = l_s[...] * alpha + psum
            acc_s[...] = acc_s[...] * alpha + d
        else:
            l_s[...] = l_s[...] + psum
            acc_s[...] = acc_s[...] + d

    # 4-way branch: (first tile?) x (update tile?) — the update tile
    # reads the just-merged slabs back from the aliased out refs
    @pl.when(jnp.logical_and(first, upd))
    def _first_updated():
        chain(ko_ref[0, 0], vo_ref[0, 0], True)

    @pl.when(jnp.logical_and(first, jnp.logical_not(upd)))
    def _first_raw():
        chain(k_ref[0, 0], v_ref[0, 0], True)

    @pl.when(jnp.logical_and(jnp.logical_not(first), upd))
    def _more_updated():
        chain(ko_ref[0, 0], vo_ref[0, 0], False)

    @pl.when(jnp.logical_and(
            jnp.logical_not(first),
            jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1), jnp.logical_not(upd))))
    def _more_raw():
        chain(k_ref[0, 0], v_ref[0, 0], False)

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        o_ref[0] = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))


def paged_attend_update(q_bd, new_k, new_v, k_pool, v_pool, tables,
                        positions, layer, *, n_steps=None):
    """Fused pool-update + paged attention for one decode layer: writes
    each sequence's new k/v column IN PLACE (the pools alias through
    the custom call) and attends over the prefix INCLUDING it.

    q_bd [B, NH, KVD] pre-scaled; new_k/new_v [B, KVD]; positions [B]
    i32 = the NEW token's position per row (its block must already be
    in the table). Every row writes — padding rows must point their
    tables at the reserved null block 0 with positions 0. Returns
    (attn [B, NH, KVD] f32, k_pool, v_pool)."""
    b, nh, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    # schedule over len+1 so the written position's block is the walk's
    # last live tile even when it was freshly allocated
    sched = paged_schedule(positions + 1, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    def new_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0)

    def upd_map(j, lp_ref, sc_ref):
        # constant per sequence: the block holding the new column; the
        # buffer is fully written on the seq's last live step, then
        # flushes when the presented index moves to the next sequence
        return (lp_ref[0], sc_ref[_UBLK, j], 0, 0)

    kernel = functools.partial(_paged_update_kernel, block_size=bs,
                               online=softmax_mode() == "online")
    with _mosaic_ctx():
        out, kp, vp = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, nh, kvd), q_map),
                    pl.BlockSpec((1, kvd), new_map),
                    pl.BlockSpec((1, kvd), new_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, nh, kvd), q_map),
                    pl.BlockSpec((1, 1, kvd, bs), upd_map),
                    pl.BlockSpec((1, 1, kvd, bs), upd_map),
                ],
                scratch_shapes=[
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, kvd), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
                jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
            ],
            # operand indices count scalar-prefetch first: 0=lp,
            # 1=sched, 2=q, 3=new_k, 4=new_v, 5=k_pool, 6=v_pool
            input_output_aliases={5: 1, 6: 2},
            cost_estimate=_cost_estimate(
                flops=4 * nh * kvd * bs * n_steps,
                transcendentals=nh * bs * n_steps,
                bytes_accessed=(2 * kvd * bs * it * n_steps
                                + 4 * b * kvd * bs * it),
                name="paged.attend_update"),
            interpret=_interpret(),
        )(lp, sched, q_bd, new_k, new_v, k_pool, v_pool)
    return out, kp, vp


# -- int8 paged KV (PR 16) ----------------------------------------------------
#
# Storage halves to one byte per cached element, with f32 scales at
# per-block / per-kv-head / per-COLUMN granularity ([L, NP, NKV, bs]).
# Per-column scales are the load-bearing choice: every column is
# quantized exactly once, from its own fp values, by the same helper on
# both the prefill-scatter and decode-update paths — so the cache BYTES
# are a pure function of the token prefix, independent of chunk
# grouping or prefill-vs-decode history. That is what keeps prefix-hit
# reuse and journal recovery bit-identical with int8 on (PARITY.md).
# Conventions follow quantization/quanters.py: qmax = 2^(b-1)-1 = 127,
# scale floor 1e-8.

KV_QMAX = 127.0
KV_SCALE_FLOOR = 1e-8

# double-buffered window budget for the paged kernels' fitter: one
# TPU core's scoped VMEM (pallas guide) — far under PTA002's 64 MiB
# static ceiling, because these windows must ALSO leave room for the
# decode batch's other kernels resident in the same step
PAGED_VMEM_BUDGET = 16 * 1024 * 1024


def kv_quant_columns(x, nkv):
    """Symmetric per-column-per-kv-head int8 quantization of KV columns.

    x [N, KVD] fp values (KVD = nkv * hd) -> (q int8 [N, KVD],
    scales f32 [N, NKV]) with scale = max(absmax/127, 1e-8) over each
    column's hd-slice — the quantization/ absmax convention. The ONLY
    quantizer for paged KV bytes: prefill scatter and decode update
    both route through it, so identical fp columns always produce
    identical int8 bytes + scales."""
    n, kvd = x.shape
    hd = kvd // int(nkv)
    xf = x.astype(jnp.float32).reshape(n, int(nkv), hd)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / KV_QMAX,
                    KV_SCALE_FLOOR)                        # [N, nkv]
    q = jnp.clip(jnp.round(xf / s[:, :, None]), -KV_QMAX, KV_QMAX)
    return q.astype(jnp.int8).reshape(n, kvd), s


def _fit_paged_kv_blocks(nh, kvd, nkv, bs, itemsize):
    """Window fitter for the quantized paged kernels (PTA002 contract).

    Block geometry is pinned by the pool layout — block_size IS the
    allocator's unit and KVD the model's — so unlike _fit_block_t this
    fitter sizes nothing; it PRICES the per-step double-buffered
    windows (q + int8 k/v tiles + f32 scale tiles + outputs + scratch)
    and fails at trace time if a configuration could not fit, instead
    of compile-failing only on hardware. Returns (kvd, bs, nkv)
    unchanged.

    Under tensor-parallel serving (PR 19) this fitter runs INSIDE the
    shard_map island, so nh/nkv here are the per-rank head counts
    (NH/mp, NKV/mp) read off the rank's pool slice — per-shard window
    budgets fall out of the argument shapes with no TP-specific fitter
    code, and a geometry that only fits when sharded is accepted
    exactly when the sharded kernel actually runs."""
    win = (2 * nh * kvd * 4                 # q window (f32-priced)
           + 2 * 2 * kvd * bs * itemsize    # k/v tiles
           + 2 * 2 * nkv * bs * 4           # scale tiles
           + 2 * nh * kvd * 4               # attn out
           + 2 * 2 * (kvd * bs * itemsize + nkv * bs * 4)  # aliased outs
           + 2 * nh * 128 * 4 + nh * kvd * 4)              # scratch
    if win > PAGED_VMEM_BUDGET:
        raise ValueError(
            f"paged int8 kernel windows need {win} B VMEM "
            f"(> {PAGED_VMEM_BUDGET} B): shrink block_size or heads")
    return kvd, bs, nkv


def _dequant_tile(tile, scale, nkv):
    """Fused in-kernel dequant of one [KVD, bs] int8 tile with its
    [NKV, bs] f32 per-column scales: expand scales across each head's
    hd rows. Reshape-based broadcast (per-head row grouping); runs in
    interpret mode and lowers to a relayout+mul on Mosaic."""
    kvd, bs = tile.shape
    hd = kvd // nkv
    return (tile.astype(jnp.float32).reshape(nkv, hd, bs)
            * scale[:, None, :]).reshape(kvd, bs)


def _paged_quant_kernel(lp_ref, sc_ref, q_ref, k_ref, v_ref, ks_ref,
                        vs_ref, o_ref, l_s, b_s, acc_s, *, block_size,
                        nkv, online=False):
    """_paged_kernel with int8 tiles: identical op chain, except k/v
    dequantize in-register before the dots (p stays f32 — there is no
    low-precision v to cast to)."""
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]

    def scores():
        k_deq = _dequant_tile(k_ref[0, 0], ks_ref[0, 0], nkv)
        s = jax.lax.dot_general(
            q_ref[0].astype(jnp.float32), k_deq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        v_deq = _dequant_tile(v_ref[0, 0], vs_ref[0, 0], nkv)
        return jax.lax.dot_general(
            p, v_deq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, KVD]

    @pl.when(sc_ref[_FIRST, j] == np.int32(1))
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p)

    @pl.when(jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1),
                             sc_ref[_FIRST, j] == np.int32(0)))
    def _more():
        s = scores()
        if online:
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p)
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p)

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        o_ref[0] = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))


def paged_attention_quant(q_bd, k_pool, v_pool, k_scale, v_scale,
                          tables, lengths, layer, *, n_steps=None):
    """Read-only paged decode attention over an int8 pool with fused
    per-column dequant. Same contract as :func:`paged_attention`, plus
    scale pools [L, NP, NKV, bs] f32 riding their own (tiny) windows
    down the same flat schedule."""
    b, nh, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    nkv = k_scale.shape[2]
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    kvd_b, bs_b, nkv_b = _fit_paged_kv_blocks(nh, kvd, nkv, bs, it)
    sched = paged_schedule(lengths, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    kernel = functools.partial(_paged_quant_kernel, block_size=bs,
                               nkv=nkv, online=softmax_mode() == "online")
    with _mosaic_ctx():
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, nh, kvd_b), q_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                ],
                out_specs=pl.BlockSpec((1, nh, kvd_b), q_map),
                scratch_shapes=[
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, kvd), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
            cost_estimate=_cost_estimate(
                flops=(4 * nh * kvd * bs + 2 * kvd * bs) * n_steps,
                transcendentals=nh * bs * n_steps,
                bytes_accessed=(2 * kvd * bs * it
                                + 2 * nkv * bs * 4) * n_steps,
                name="paged.attention_quant"),
            interpret=_interpret(),
        )(lp, sched, q_bd, k_pool, v_pool, k_scale, v_scale)
    return out


def _paged_update_quant_kernel(lp_ref, sc_ref, q_ref, nk_ref, nv_ref,
                               nks_ref, nvs_ref, k_ref, v_ref, ks_ref,
                               vs_ref, o_ref, ko_ref, vo_ref, kso_ref,
                               vso_ref, l_s, b_s, acc_s, *, block_size,
                               nkv, online=False):
    """_paged_update_kernel over int8 tiles + f32 scale tiles. The new
    column arrives ALREADY quantized (kv_quant_columns outside the
    call, so decode writes the same bytes a prefill of the same tokens
    would); the kernel merges bytes + scale into the update tile and
    dequantizes whichever tile each step reads."""
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]
    col = sc_ref[_COL, j]
    first = sc_ref[_FIRST, j] == np.int32(1)
    upd = sc_ref[_LAST, j] == np.int32(1)
    kvd = q_ref.shape[2]
    lane = lax.broadcasted_iota(jnp.int32, (kvd, block_size), 1)
    lane_s = lax.broadcasted_iota(jnp.int32, (nkv, block_size), 1)

    @pl.when(upd)
    def _write_cache():
        # full tiles written every update step (the aliased out windows
        # start uninitialized); the int8 insert routes through f32 like
        # the fp16 kernel's minor-dim insert — exact for int8 values
        ko_ref[0, 0] = jnp.where(
            lane == col, nk_ref[0].astype(jnp.float32)[:, None],
            k_ref[0, 0].astype(jnp.float32)).astype(jnp.int8)
        vo_ref[0, 0] = jnp.where(
            lane == col, nv_ref[0].astype(jnp.float32)[:, None],
            v_ref[0, 0].astype(jnp.float32)).astype(jnp.int8)
        kso_ref[0, 0] = jnp.where(lane_s == col, nks_ref[0][:, None],
                                  ks_ref[0, 0])
        vso_ref[0, 0] = jnp.where(lane_s == col, nvs_ref[0][:, None],
                                  vs_ref[0, 0])

    def chain(k_at, v_at, ks_at, vs_at, is_first):
        k_deq = _dequant_tile(k_at, ks_at, nkv)
        v_deq = _dequant_tile(v_at, vs_at, nkv)
        s = jax.lax.dot_general(
            q_ref[0].astype(jnp.float32), k_deq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [NH, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(t <= pos, s, jnp.float32(-1e30))
        alpha = None
        if is_first:
            bvec = s.max(axis=-1, keepdims=True)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        elif online:
            m_prev = b_s[:, :1]
            bvec = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - bvec)
            b_s[...] = jnp.broadcast_to(bvec, b_s.shape)
        else:
            bvec = b_s[:, :1]
        p = jnp.exp2(s - bvec)
        psum = jnp.broadcast_to(p.sum(axis=-1, keepdims=True), l_s.shape)
        d = jax.lax.dot_general(
            p, v_deq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if is_first:
            l_s[...] = psum
            acc_s[...] = d
        elif online:
            l_s[...] = l_s[...] * alpha + psum
            acc_s[...] = acc_s[...] * alpha + d
        else:
            l_s[...] = l_s[...] + psum
            acc_s[...] = acc_s[...] + d

    @pl.when(jnp.logical_and(first, upd))
    def _first_updated():
        chain(ko_ref[0, 0], vo_ref[0, 0], kso_ref[0, 0], vso_ref[0, 0],
              True)

    @pl.when(jnp.logical_and(first, jnp.logical_not(upd)))
    def _first_raw():
        chain(k_ref[0, 0], v_ref[0, 0], ks_ref[0, 0], vs_ref[0, 0], True)

    @pl.when(jnp.logical_and(jnp.logical_not(first), upd))
    def _more_updated():
        chain(ko_ref[0, 0], vo_ref[0, 0], kso_ref[0, 0], vso_ref[0, 0],
              False)

    @pl.when(jnp.logical_and(
            jnp.logical_not(first),
            jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1),
                            jnp.logical_not(upd))))
    def _more_raw():
        chain(k_ref[0, 0], v_ref[0, 0], ks_ref[0, 0], vs_ref[0, 0], False)

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        o_ref[0] = acc_s[...] / jnp.maximum(l_s[:, :1], jnp.float32(1e-30))


def paged_attend_update_quant(q_bd, new_k, new_v, new_ks, new_vs,
                              k_pool, v_pool, k_scale, v_scale, tables,
                              positions, layer, *, n_steps=None):
    """Fused int8 pool-update + paged attention for one decode layer.

    Same contract as :func:`paged_attend_update`, except the pools are
    int8 with [L, NP, NKV, bs] f32 scale pools, and the new columns
    arrive pre-quantized: new_k/new_v int8 [B, KVD], new_ks/new_vs f32
    [B, NKV] from :func:`kv_quant_columns`. All four pools alias
    through the custom call. Returns (attn [B, NH, KVD] f32, k_pool,
    v_pool, k_scale, v_scale)."""
    b, nh, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    nkv = k_scale.shape[2]
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    kvd_b, bs_b, nkv_b = _fit_paged_kv_blocks(nh, kvd, nkv, bs, it)
    sched = paged_schedule(positions + 1, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    def new_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0)

    def upd_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_UBLK, j], 0, 0)

    kernel = functools.partial(_paged_update_quant_kernel, block_size=bs,
                               nkv=nkv, online=softmax_mode() == "online")
    with _mosaic_ctx():
        out, kp, vp, ks, vs = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, nh, kvd_b), q_map),
                    pl.BlockSpec((1, kvd_b), new_map),
                    pl.BlockSpec((1, kvd_b), new_map),
                    pl.BlockSpec((1, nkv_b), new_map),
                    pl.BlockSpec((1, nkv_b), new_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, nh, kvd_b), q_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), upd_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), upd_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), upd_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), upd_map),
                ],
                scratch_shapes=[
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, 128), jnp.float32),
                    pltpu.VMEM((nh, kvd), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, nh, kvd), jnp.float32),
                jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ],
            # operand indices count scalar-prefetch first: 0=lp,
            # 1=sched, 2=q, 3=new_k, 4=new_v, 5=new_ks, 6=new_vs,
            # 7=k_pool, 8=v_pool, 9=k_scale, 10=v_scale
            input_output_aliases={7: 1, 8: 2, 9: 3, 10: 4},
            cost_estimate=_cost_estimate(
                flops=(4 * nh * kvd * bs + 2 * kvd * bs) * n_steps,
                transcendentals=nh * bs * n_steps,
                bytes_accessed=((2 * kvd * bs * it + 2 * nkv * bs * 4)
                                * n_steps
                                + 4 * b * (kvd + nkv) * bs * it),
                name="paged.attend_update_quant"),
            interpret=_interpret(),
        )(lp, sched, q_bd, new_k, new_v, new_ks, new_vs,
          k_pool, v_pool, k_scale, v_scale)
    return out, kp, vp, ks, vs


def paged_attention_xla(q, k_pool, v_pool, tables, lengths, layer,
                        scale):
    """Plain-XLA reference: q [B, NH, KVD] UNSCALED, standard e-base
    softmax in f32. Gathers each table's blocks into a contiguous
    [B, KVD, max_nb*bs] view — the layout-parity oracle for the
    kernels (allclose, not bitwise: different exponent base)."""
    B, max_nb = tables.shape
    bs = k_pool.shape[-1]
    kc = jnp.transpose(k_pool[layer][tables], (0, 2, 1, 3)) \
        .reshape(B, k_pool.shape[2], max_nb * bs)
    vc = jnp.transpose(v_pool[layer][tables], (0, 2, 1, 3)) \
        .reshape(B, v_pool.shape[2], max_nb * bs)
    s = jnp.einsum("bhc,bct->bht", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) * scale
    t = jnp.arange(max_nb * bs)[None, None, :]
    s = jnp.where(t < lengths[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bht,bct->bhc", p, vc.astype(jnp.float32))


# -- speculative verification (PR 18) -----------------------------------------
#
# Greedy speculative decoding scores K+1 fed tokens per sequence in ONE
# pass: the kernel below attends every fed token's query rows over the
# CACHED prefix only (the unchanged flat schedule — fed tokens are not
# in the pool yet), and returns UNFINALIZED online-softmax partials
# (acc, m, l) so the caller can merge the fed-token attention — computed
# outside in XLA, where the tiny [T, T] causal block is cheap — exactly:
# rescale both partial sums to a common max and finalize once. The merge
# identity holds for the tile-0-anchored m just as for a true running
# max, so both PADDLE_TPU_FLASH_SOFTMAX modes verify bit-stably.
#
# Commit is a second fused kernel: scalar-prefetched per-sequence accept
# lengths redirect every rejected or dead column to the reserved null
# block 0 (the engine's scribble target), so ONLY accepted tokens' KV
# lands in live blocks — int8 columns arrive pre-quantized by
# kv_quant_columns, keeping committed bytes equal to what sequential
# decode would have written (PARITY.md).

# commit sched row indices ([N_COMMIT_FIELDS, L*B*T] i32)
_CL, _CB, _CCOL, _CFIRST, _CSEQ, _CT = range(6)
N_COMMIT_FIELDS = 6


def _fit_paged_verify_blocks(r, kvd, nkv, bs, itemsize):
    """Window fitter for the verification kernels (PTA002 contract).

    Like _fit_paged_kv_blocks the geometry is pinned by the pool layout;
    this prices the verify read's double-buffered windows — r = T*NH
    query rows instead of NH, plus the three partial outputs — and
    fails at trace time if they could not fit. Returns (kvd, bs, nkv)
    unchanged. Under tensor-parallel serving (PR 19) r and nkv are the
    per-rank values seen inside the shard_map island, so verify
    windows are priced per shard automatically."""
    win = (2 * r * kvd * 4                  # q window
           + 2 * 2 * kvd * bs * itemsize    # k/v tiles
           + 2 * 2 * nkv * bs * 4           # scale tiles (quant path)
           + 2 * r * (kvd + 2 * 128) * 4    # acc/m/l partial outs
           + 2 * r * 128 * 4 + r * kvd * 4)  # scratch
    if win > PAGED_VMEM_BUDGET:
        raise ValueError(
            f"paged verify kernel windows need {win} B VMEM "
            f"(> {PAGED_VMEM_BUDGET} B): shrink draft_k, block_size or "
            f"heads")
    return kvd, bs, nkv


def _paged_verify_kernel(lp_ref, sc_ref, q_ref, k_ref, v_ref,
                         acc_ref, m_ref, l_ref, l_s, b_s, acc_s, *,
                         block_size, online=False):
    """_paged_kernel over R = T*NH query rows, finalization deferred:
    the last live step stores raw (acc, m, l) instead of acc/l."""
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]

    def scores():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [R, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        return jax.lax.dot_general(
            p, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [R, KVD]

    @pl.when(sc_ref[_FIRST, j] == np.int32(1))
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p.astype(v_ref.dtype))

    @pl.when(jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1),
                             sc_ref[_FIRST, j] == np.int32(0)))
    def _more():
        s = scores()
        if online:
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p.astype(v_ref.dtype))
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p.astype(v_ref.dtype))

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        acc_ref[0] = acc_s[...]
        m_ref[0] = b_s[...]
        l_ref[0] = l_s[...]


def paged_attention_verify(q_bd, k_pool, v_pool, tables, qstart, layer,
                           *, n_steps=None):
    """Multi-token verification read over the CACHED prefix of each
    sequence.

    q_bd [B, R, KVD] with R = T*NH t-major block-diagonal rows (row
    r = t*NH + h is fed token t's head-h query), PRE-SCALED by
    scale*log2(e); qstart [B] i32 cached token counts (a 0 row is
    skipped and its outputs left unwritten — every live row must have
    qstart >= 1). All R rows of a sequence share the prefix mask
    t < qstart; the caller merges fed-token attention outside. Returns
    UNFINALIZED f32 partials (acc [B, R, KVD], m [B, R, 128],
    l [B, R, 128]) — only column 0 of m/l is meaningful."""
    b, r, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    sched = paged_schedule(qstart, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    kernel = functools.partial(_paged_verify_kernel, block_size=bs,
                               online=softmax_mode() == "online")
    with _mosaic_ctx():
        acc, m, l = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, r, kvd), q_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                    pl.BlockSpec((1, 1, kvd, bs), kv_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, r, kvd), q_map),
                    pl.BlockSpec((1, r, 128), q_map),
                    pl.BlockSpec((1, r, 128), q_map),
                ],
                scratch_shapes=[
                    pltpu.VMEM((r, 128), jnp.float32),
                    pltpu.VMEM((r, 128), jnp.float32),
                    pltpu.VMEM((r, kvd), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, r, kvd), jnp.float32),
                jax.ShapeDtypeStruct((b, r, 128), jnp.float32),
                jax.ShapeDtypeStruct((b, r, 128), jnp.float32),
            ],
            cost_estimate=_cost_estimate(
                flops=4 * r * kvd * bs * n_steps,
                transcendentals=r * bs * n_steps,
                bytes_accessed=2 * kvd * bs * it * n_steps,
                name="paged.attention_verify"),
            interpret=_interpret(),
        )(lp, sched, q_bd, k_pool, v_pool)
    return acc, m, l


def _paged_verify_quant_kernel(lp_ref, sc_ref, q_ref, k_ref, v_ref,
                               ks_ref, vs_ref, acc_ref, m_ref, l_ref,
                               l_s, b_s, acc_s, *, block_size, nkv,
                               online=False):
    """_paged_verify_kernel over int8 tiles (fused per-column dequant,
    same op chain as _paged_quant_kernel, finalization deferred)."""
    j = pl.program_id(0)
    pos = sc_ref[_POS, j]
    start = sc_ref[_START, j]

    def scores():
        k_deq = _dequant_tile(k_ref[0, 0], ks_ref[0, 0], nkv)
        s = jax.lax.dot_general(
            q_ref[0].astype(jnp.float32), k_deq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [R, bs]
        t = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        return jnp.where(t <= pos, s, jnp.float32(-1e30))

    def pv(p):
        v_deq = _dequant_tile(v_ref[0, 0], vs_ref[0, 0], nkv)
        return jax.lax.dot_general(
            p, v_deq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [R, KVD]

    @pl.when(sc_ref[_FIRST, j] == np.int32(1))
    def _first():
        s = scores()
        base = s.max(axis=-1, keepdims=True)
        p = jnp.exp2(s - base)
        b_s[...] = jnp.broadcast_to(base, b_s.shape)
        l_s[...] = jnp.broadcast_to(p.sum(axis=-1, keepdims=True),
                                    l_s.shape)
        acc_s[...] = pv(p)

    @pl.when(jnp.logical_and(sc_ref[_LIVE, j] == np.int32(1),
                             sc_ref[_FIRST, j] == np.int32(0)))
    def _more():
        s = scores()
        if online:
            m_prev = b_s[:, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            p = jnp.exp2(s - m_new)
            b_s[...] = jnp.broadcast_to(m_new, b_s.shape)
            l_s[...] = l_s[...] * alpha + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] * alpha + pv(p)
        else:
            p = jnp.exp2(s - b_s[:, :1])
            l_s[...] = l_s[...] + jnp.broadcast_to(
                p.sum(axis=-1, keepdims=True), l_s.shape)
            acc_s[...] = acc_s[...] + pv(p)

    @pl.when(sc_ref[_LAST, j] == np.int32(1))
    def _fin():
        acc_ref[0] = acc_s[...]
        m_ref[0] = b_s[...]
        l_ref[0] = l_s[...]


def paged_attention_verify_quant(q_bd, k_pool, v_pool, k_scale, v_scale,
                                 tables, qstart, layer, *, n_steps=None):
    """Multi-token verification read over an int8 pool with fused
    per-column dequant. Same contract as
    :func:`paged_attention_verify`, plus the [L, NP, NKV, bs] f32 scale
    pools riding the flat schedule."""
    b, r, kvd = q_bd.shape
    L, NP, _, bs = k_pool.shape
    nkv = k_scale.shape[2]
    B, max_nb = tables.shape
    if n_steps is None:
        n_steps = B * max_nb
    it = jnp.dtype(k_pool.dtype).itemsize
    kvd_b, bs_b, nkv_b = _fit_paged_verify_blocks(r, kvd, nkv, bs, it)
    sched = paged_schedule(qstart, tables, n_steps, bs)
    lp = jnp.asarray([layer], jnp.int32)

    def kv_map(j, lp_ref, sc_ref):
        return (lp_ref[0], sc_ref[_BLK, j], 0, 0)

    def q_map(j, lp_ref, sc_ref):
        return (sc_ref[_SEQ, j], 0, 0)

    kernel = functools.partial(_paged_verify_quant_kernel, block_size=bs,
                               nkv=nkv, online=softmax_mode() == "online")
    with _mosaic_ctx():
        acc, m, l = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_steps,),
                in_specs=[
                    pl.BlockSpec((1, r, kvd_b), q_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), kv_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, r, kvd_b), q_map),
                    pl.BlockSpec((1, r, 128), q_map),
                    pl.BlockSpec((1, r, 128), q_map),
                ],
                scratch_shapes=[
                    pltpu.VMEM((r, 128), jnp.float32),
                    pltpu.VMEM((r, 128), jnp.float32),
                    pltpu.VMEM((r, kvd), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, r, kvd), jnp.float32),
                jax.ShapeDtypeStruct((b, r, 128), jnp.float32),
                jax.ShapeDtypeStruct((b, r, 128), jnp.float32),
            ],
            cost_estimate=_cost_estimate(
                flops=(4 * r * kvd * bs + 2 * kvd * bs) * n_steps,
                transcendentals=r * bs * n_steps,
                bytes_accessed=(2 * kvd * bs * it
                                + 2 * nkv * bs * 4) * n_steps,
                name="paged.attention_verify_quant"),
            interpret=_interpret(),
        )(lp, sched, q_bd, k_pool, v_pool, k_scale, v_scale)
    return acc, m, l


def merge_verify_partials(acc_c, m_c, l_c, acc_f, m_f, l_f):
    """Exact online-softmax merge of the kernel's cached-prefix partials
    with the caller's fed-token partials: rescale both exp2 sums to the
    common max and finalize once. Exact for ANY anchor m (tile-0 or
    running max): acc = sum_i exp2(s_i - m) * v_i rescales by
    exp2(m - m_tot) regardless of how m was chosen. Shapes: acc
    [B, R, KVD]; m/l [B, R, 1]. Returns attn [B, R, KVD] f32."""
    m_tot = jnp.maximum(m_c, m_f)
    a_c = jnp.exp2(m_c - m_tot)
    a_f = jnp.exp2(m_f - m_tot)
    num = acc_c * a_c + acc_f * a_f
    den = l_c * a_c + l_f * a_f
    return num / jnp.maximum(den, jnp.float32(1e-30))


def paged_commit_schedule(qstart, commit_len, tables, n_layers,
                          n_tokens, block_size):
    """Flat commit walk for the verification cache update:
    [N_COMMIT_FIELDS, L*B*T] i32, layer-major then sequence then token.

    Fed token t of sequence b commits at position qstart[b] + t iff
    t < commit_len[b]; rejected and dead columns redirect to the
    reserved null block 0 (the engine's scribble target), so the kernel
    writes every step and live blocks only ever receive accepted
    columns. Within one (layer, seq) the walk's block ids are
    non-decreasing and each block is visited consecutively, so the
    FIRST flag (out-window change) is computable by shifted comparison.
    Works on traced values (pure jnp)."""
    B, max_nb = tables.shape
    bs = jnp.int32(block_size)
    n = int(n_layers) * B * int(n_tokens)
    idx = jnp.arange(n, dtype=jnp.int32)
    li = idx // (B * int(n_tokens))
    bi = (idx // int(n_tokens)) % B
    ti = idx % int(n_tokens)
    pos = qstart[bi].astype(jnp.int32) + ti
    commit = ti < commit_len[bi].astype(jnp.int32)
    slot = jnp.clip(pos // bs, 0, max_nb - 1)
    bid = jnp.where(commit, tables[bi, slot].astype(jnp.int32),
                    jnp.int32(0))
    col = pos % bs
    prev_l = jnp.concatenate([jnp.full((1,), -1, jnp.int32), li[:-1]])
    prev_b = jnp.concatenate([jnp.full((1,), -1, jnp.int32), bid[:-1]])
    first = ((li != prev_l) | (bid != prev_b)).astype(jnp.int32)
    return jnp.stack([li, bid, col, first, bi, ti])


def _paged_commit_kernel(sc_ref, nk_ref, nv_ref, k_ref, v_ref,
                         ko_ref, vo_ref, *, block_size):
    """One fed token's column merged into its block tile per step. The
    first visit to an out window seeds it from the input pool tile;
    revisits (further columns of the same block) read the aliased out
    refs back — the paged_attend_update revisit-buffer semantics. The
    minor-dim insert routes through f32 (Mosaic bf16 limitation), exact
    for f32 and int8 values alike."""
    j = pl.program_id(0)
    col = sc_ref[_CCOL, j]
    first = sc_ref[_CFIRST, j] == np.int32(1)
    kvd = nk_ref.shape[3]
    lane = lax.broadcasted_iota(jnp.int32, (kvd, block_size), 1)

    def merged(base, new_ref):
        new32 = new_ref[0, 0, 0].astype(jnp.float32)[:, None]
        return jnp.where(lane == col, new32, base.astype(jnp.float32)) \
            .astype(ko_ref.dtype)

    @pl.when(first)
    def _fresh():
        ko_ref[0, 0] = merged(k_ref[0, 0], nk_ref)
        vo_ref[0, 0] = merged(v_ref[0, 0], nv_ref)

    @pl.when(jnp.logical_not(first))
    def _revisit():
        ko_ref[0, 0] = merged(ko_ref[0, 0], nk_ref)
        vo_ref[0, 0] = merged(vo_ref[0, 0], nv_ref)


def paged_verify_commit(new_k, new_v, k_pool, v_pool, tables, qstart,
                        commit_len):
    """Fused post-verification cache commit: writes fed token t's KV
    column at position qstart[b] + t for every t < commit_len[b],
    across all layers in one call. new_k/new_v [L, B, T, KVD] in pool
    dtype; rejected/dead columns scribble the reserved null block 0.
    The pools alias through the custom call. Returns (k_pool,
    v_pool)."""
    L, B, T, kvd = new_k.shape
    _, NP, _, bs = k_pool.shape
    n = L * B * T
    it = jnp.dtype(k_pool.dtype).itemsize
    sched = paged_commit_schedule(qstart, commit_len, tables, L, T, bs)

    def new_map(j, sc_ref):
        return (sc_ref[_CL, j], sc_ref[_CSEQ, j], sc_ref[_CT, j], 0)

    def pool_map(j, sc_ref):
        return (sc_ref[_CL, j], sc_ref[_CB, j], 0, 0)

    with _mosaic_ctx():
        kp, vp = pl.pallas_call(
            functools.partial(_paged_commit_kernel, block_size=bs),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n,),
                in_specs=[
                    pl.BlockSpec((1, 1, 1, kvd), new_map),
                    pl.BlockSpec((1, 1, 1, kvd), new_map),
                    pl.BlockSpec((1, 1, kvd, bs), pool_map),
                    pl.BlockSpec((1, 1, kvd, bs), pool_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, 1, kvd, bs), pool_map),
                    pl.BlockSpec((1, 1, kvd, bs), pool_map),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
            ],
            # operand indices count scalar-prefetch first: 0=sched,
            # 1=new_k, 2=new_v, 3=k_pool, 4=v_pool
            input_output_aliases={3: 0, 4: 1},
            cost_estimate=_cost_estimate(
                flops=2 * kvd * bs * n,
                transcendentals=0,
                bytes_accessed=(2 * kvd * bs * it + 2 * kvd * it) * n,
                name="paged.verify_commit"),
            interpret=_interpret(),
        )(sched, new_k, new_v, k_pool, v_pool)
    return kp, vp


def _paged_commit_quant_kernel(sc_ref, nk_ref, nv_ref, nks_ref, nvs_ref,
                               k_ref, v_ref, ks_ref, vs_ref,
                               ko_ref, vo_ref, kso_ref, vso_ref, *,
                               block_size, nkv):
    """_paged_commit_kernel over int8 byte tiles + f32 scale tiles. The
    fed columns arrive ALREADY quantized (kv_quant_columns outside the
    call), so committed bytes equal what sequential decode would have
    written; the int8 insert routes through f32 — exact for int8
    values."""
    j = pl.program_id(0)
    col = sc_ref[_CCOL, j]
    first = sc_ref[_CFIRST, j] == np.int32(1)
    kvd = nk_ref.shape[3]
    lane = lax.broadcasted_iota(jnp.int32, (kvd, block_size), 1)
    lane_s = lax.broadcasted_iota(jnp.int32, (nkv, block_size), 1)

    def merged(base, new_ref):
        new32 = new_ref[0, 0, 0].astype(jnp.float32)[:, None]
        return jnp.where(lane == col, new32, base.astype(jnp.float32)) \
            .astype(jnp.int8)

    def merged_s(base, new_ref):
        return jnp.where(lane_s == col, new_ref[0, 0, 0][:, None], base)

    @pl.when(first)
    def _fresh():
        ko_ref[0, 0] = merged(k_ref[0, 0], nk_ref)
        vo_ref[0, 0] = merged(v_ref[0, 0], nv_ref)
        kso_ref[0, 0] = merged_s(ks_ref[0, 0], nks_ref)
        vso_ref[0, 0] = merged_s(vs_ref[0, 0], nvs_ref)

    @pl.when(jnp.logical_not(first))
    def _revisit():
        ko_ref[0, 0] = merged(ko_ref[0, 0], nk_ref)
        vo_ref[0, 0] = merged(vo_ref[0, 0], nv_ref)
        kso_ref[0, 0] = merged_s(kso_ref[0, 0], nks_ref)
        vso_ref[0, 0] = merged_s(vso_ref[0, 0], nvs_ref)


def paged_verify_commit_quant(new_k, new_v, new_ks, new_vs, k_pool,
                              v_pool, k_scale, v_scale, tables, qstart,
                              commit_len):
    """Fused int8 post-verification cache commit. Same contract as
    :func:`paged_verify_commit`, except the fed columns arrive
    pre-quantized — new_k/new_v int8 [L, B, T, KVD] with new_ks/new_vs
    f32 [L, B, T, NKV] from :func:`kv_quant_columns` — and all four
    pools alias through the custom call. Returns (k_pool, v_pool,
    k_scale, v_scale)."""
    L, B, T, kvd = new_k.shape
    _, NP, _, bs = k_pool.shape
    nkv = k_scale.shape[2]
    n = L * B * T
    it = jnp.dtype(k_pool.dtype).itemsize
    kvd_b, bs_b, nkv_b = _fit_paged_kv_blocks(1, kvd, nkv, bs, it)
    sched = paged_commit_schedule(qstart, commit_len, tables, L, T, bs)

    def new_map(j, sc_ref):
        return (sc_ref[_CL, j], sc_ref[_CSEQ, j], sc_ref[_CT, j], 0)

    def pool_map(j, sc_ref):
        return (sc_ref[_CL, j], sc_ref[_CB, j], 0, 0)

    with _mosaic_ctx():
        kp, vp, ks, vs = pl.pallas_call(
            functools.partial(_paged_commit_quant_kernel, block_size=bs,
                              nkv=nkv),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n,),
                in_specs=[
                    pl.BlockSpec((1, 1, 1, kvd_b), new_map),
                    pl.BlockSpec((1, 1, 1, kvd_b), new_map),
                    pl.BlockSpec((1, 1, 1, nkv_b), new_map),
                    pl.BlockSpec((1, 1, 1, nkv_b), new_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), pool_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, 1, kvd_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, kvd_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), pool_map),
                    pl.BlockSpec((1, 1, nkv_b, bs_b), pool_map),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ],
            # operand indices count scalar-prefetch first: 0=sched,
            # 1=new_k, 2=new_v, 3=new_ks, 4=new_vs, 5=k_pool, 6=v_pool,
            # 7=k_scale, 8=v_scale
            input_output_aliases={5: 0, 6: 1, 7: 2, 8: 3},
            cost_estimate=_cost_estimate(
                flops=2 * kvd * bs * n,
                transcendentals=0,
                bytes_accessed=((2 * kvd * bs + 2 * nkv * bs * 4) * it
                                + 2 * (kvd + 4 * nkv) * it) * n,
                name="paged.verify_commit_quant"),
            interpret=_interpret(),
        )(sched, new_k, new_v, new_ks, new_vs,
          k_pool, v_pool, k_scale, v_scale)
    return kp, vp, ks, vs
