"""Fused RMSNorm Pallas kernel (ref: paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu).

One VMEM pass per row tile: mean-of-squares, rsqrt, scale — fp32 accumulation,
compute-dtype output. Backward via custom_vjp with the closed-form gradient
(one fused jnp expression; XLA fuses it into surrounding ops).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ._common import cost_estimate as _cost_estimate
from ._common import interpret_mode as _interpret
from ._common import mosaic_trace_ctx as _mosaic_ctx


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_fwd_impl(x2d, w, eps, block_rows):
    n, h = x2d.shape
    grid = (pl.cdiv(n, block_rows),)
    # Mosaic rejects 1-D blocks; feed the weight as a [1, H] tile.
    with _mosaic_ctx():
        return pl.pallas_call(
            functools.partial(_rms_kernel, eps=eps),
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), x2d.dtype),
            # square + mean-acc + two scale muls per element; rsqrt per row
            cost_estimate=_cost_estimate(
                flops=4 * n * h,
                transcendentals=n,
                bytes_accessed=2 * n * h * jnp.dtype(x2d.dtype).itemsize,
                name="rms_norm.fwd"),
            interpret=_interpret(),
        )(x2d, w.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm(x2d, w, eps):
    return _rms_fwd_impl(x2d, w, eps, block_rows=min(256, x2d.shape[0]))


def _rms_fwd(x2d, w, eps):
    out = _rms_norm(x2d, w, eps)
    return out, (x2d, w)


def _rms_bwd(eps, res, g):
    x2d, w = res
    x = x2d.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    h = x.shape[-1]
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    xhat = x * inv
    gw = g32 * w32
    # d/dx [x * inv]: inv * (gw - xhat * mean(gw * xhat))
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(g32 * xhat, axis=0)
    return dx.astype(x2d.dtype), dw.astype(w.dtype)


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm(x, weight, epsilon=1e-6):
    """x: [..., H] array; weight: [H]. Returns same shape/dtype as x."""
    shape = x.shape
    out = _rms_norm(x.reshape(-1, shape[-1]), weight, float(epsilon))
    return out.reshape(shape)
