"""Fused rotary position embedding (ref: paddle/phi/kernels/fusion/gpu/
fused_rope_kernel.cu; python API paddle.incubate.nn.functional.
fused_rotary_position_embedding).

The rotation is pure VPU work that XLA fuses into the surrounding attention
projections, so the "kernel" here is the fused jnp expression (a Pallas
version adds nothing: no reuse, no reduction). Neox-style half-rotation and
GPT-J-style interleaved pairs both supported, [B, S, H, D] layout.
"""
from __future__ import annotations

import jax.numpy as jnp


def build_rope_cache(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                     position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                               / head_dim))
    pos = (jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None
           else position_ids.astype(jnp.float32))
    freqs = jnp.einsum("...s,d->...sd", pos, inv_freq)    # [S, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, interleaved=False):
    """x: [B, S, H, D]; cos/sin: [S, D/2] (or broadcastable)."""
    d = x.shape[-1]
    x32 = x.astype(jnp.float32)
    if cos.ndim == 2:
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    else:
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]
    if interleaved:
        x1 = x32[..., 0::2]
        x2 = x32[..., 1::2]
        r1 = x1 * cos_b - x2 * sin_b
        r2 = x2 * cos_b + x1 * sin_b
        out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    else:  # neox half-rotation
        x1 = x32[..., : d // 2]
        x2 = x32[..., d // 2:]
        r1 = x1 * cos_b - x2 * sin_b
        r2 = x2 * cos_b + x1 * sin_b
        out = jnp.concatenate([r1, r2], axis=-1)
    return out.astype(x.dtype)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """paddle.incubate parity signature, on raw arrays [B, S, H, D]."""
    if cos is None or sin is None:
        cos_h, sin_h = build_rope_cache(q.shape[1], q.shape[-1],
                                        position_ids=position_ids)
    else:
        # reference passes [1, S, 1, D] duplicated tables; reduce to [S, D/2]
        cos_h = jnp.squeeze(cos)
        sin_h = jnp.squeeze(sin)
        if cos_h.shape[-1] == q.shape[-1]:
            cos_h = cos_h[..., : q.shape[-1] // 2]
            sin_h = sin_h[..., : q.shape[-1] // 2]
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
        else:
            outs.append(apply_rope(t, cos_h, sin_h,
                                   interleaved=not use_neox_rotary_style))
    return tuple(outs)
