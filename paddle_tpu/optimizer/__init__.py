"""Optimizers and LR schedulers (ref: python/paddle/optimizer/)."""
from . import lr
from .optimizer import Optimizer
from .optimizers import (ASGD, NAdam, RAdam, Rprop,  # noqa: F401
                         SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb,
                         LarsMomentum, Momentum, RMSProp)
