"""Learning-rate schedulers (ref: python/paddle/optimizer/lr.py).

Same stateful contract as the reference: ``scheduler.step()`` advances the
epoch/step counter; the bound optimizer reads ``scheduler()`` each step.
"""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = None
        self.verbose = verbose
        self.step()

    def __call__(self):
        return self.last_lr

    def get_lr(self):
        raise NotImplementedError

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, state):
        self.__dict__.update(state)

    load_state_dict = set_state_dict
    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5 *
                min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - step / decay_steps) ** self.power + self.end_lr)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * \
                self.last_epoch / self.warmup_steps + self.start_lr
        if isinstance(self.lr, LRScheduler):
            self.lr.step(self.last_epoch - self.warmup_steps)
            return self.lr()
        return self.lr

    def state_dict(self):
        sd = super().state_dict()
        if isinstance(self.lr, LRScheduler):
            sd["inner"] = self.lr.state_dict()
        return sd

    def set_state_dict(self, state):
        inner = state.pop("inner", None)
        super().set_state_dict(state)
        if inner is not None and isinstance(self.lr, LRScheduler):
            self.lr.set_state_dict(inner)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        sd = super().state_dict()
        sd.pop("lr_lambda", None)
        return sd


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0,
                 last_epoch=-1, verbose=False):
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        t_i = self.T_0
        while t >= t_i:
            t -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * t / t_i)) / 2


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _anneal(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return (end - start) * pct + start

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up_steps = int(self.phase_pct * self.total_steps)
        if step <= up_steps:
            return self._anneal(self.initial_lr, self.max_lr,
                                step / max(up_steps, 1))
        return self._anneal(self.max_lr, self.end_lr,
                            (step - up_steps) / max(self.total_steps - up_steps, 1))


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = float(learning_rate)
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def _is_better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return cur < self.best * (1 - self.threshold)
            return cur < self.best - self.threshold
        if self.threshold_mode == "rel":
            return cur > self.best * (1 + self.threshold)
        return cur > self.best + self.threshold

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        from ..tensor.tensor import Tensor
        cur = float(metrics.item()) if isinstance(metrics, Tensor) else float(metrics)
        self.last_epoch += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self._is_better(cur):
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                new_lr = max(self.last_lr * self.factor, self.min_lr)
                self.last_lr = new_lr
                self.cooldown_counter = self.cooldown
                self.num_bad = 0


class MultiplicativeDecay(LRScheduler):
    """lr *= lr_lambda(epoch) cumulatively (ref: lr.MultiplicativeDecay)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cum_epoch = 0
        self._cum_factor = 1.0
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        # cache the running product: O(1) per step instead of O(epoch)
        if self.last_epoch < self._cum_epoch:
            self._cum_epoch, self._cum_factor = 0, 1.0
        while self._cum_epoch < self.last_epoch:
            self._cum_epoch += 1
            self._cum_factor *= self.lr_lambda(self._cum_epoch)
        return self.base_lr * self._cum_factor


class LinearLR(LRScheduler):
    """Linear ramp from start_factor to end_factor over total_steps
    (ref: lr.LinearLR)."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        if total_steps <= 0:
            raise ValueError(
                f"LinearLR: total_steps must be positive, got {total_steps}")
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = min(max(self.last_epoch, 0), self.total_steps)
        frac = t / self.total_steps
        factor = self.start_factor + (self.end_factor - self.start_factor) * frac
        return self.base_lr * factor


class CyclicLR(LRScheduler):
    """Triangular cyclic schedule (ref: lr.CyclicLR; Smith 2015).

    modes: 'triangular' (constant amplitude), 'triangular2' (halved per
    cycle), 'exp_range' (gamma**step scaling).
    """

    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = float(max_learning_rate)
        self.step_size_up = step_size_up
        self.step_size_down = (step_size_down if step_size_down is not None
                               else step_size_up)
        self.mode = mode
        self.exp_gamma = exp_gamma
        self._scale_fn = scale_fn
        self.scale_mode = scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def _scale(self, cycle, step):
        if self._scale_fn is not None:
            return self._scale_fn(cycle if self.scale_mode == "cycle"
                                  else step)
        if self.mode == "triangular":
            return 1.0
        if self.mode == "triangular2":
            return 1.0 / (2.0 ** (cycle - 1))
        if self.mode == "exp_range":
            return self.exp_gamma ** step
        raise ValueError(f"unknown CyclicLR mode {self.mode!r}")

    def get_lr(self):
        step = max(self.last_epoch, 0)
        total = self.step_size_up + self.step_size_down
        cycle = step // total + 1
        pos = step % total
        if pos < self.step_size_up:
            frac = pos / self.step_size_up
        else:
            frac = 1.0 - (pos - self.step_size_up) / self.step_size_down
        amp = (self.max_lr - self.base_lr) * frac
        return self.base_lr + amp * self._scale(cycle, step)
