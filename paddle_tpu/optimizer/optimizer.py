"""Optimizer base (ref: python/paddle/optimizer/optimizer.py).

Each optimizer defines a pure functional update ``_update(param, grad, state,
lr) -> (new_param, new_state)``; the eager ``step()`` maps it over parameters
(jit-compiled per shape/dtype so the hot loop is all XLA), and the same
functional core drives compiled training steps (jit/train_step.py) — one
implementation for both paths, unlike the reference's separate dygraph/static
optimizer ops.

multi_precision mirrors the reference: bf16/fp16 params keep an fp32 master
copy in the optimizer state; updates apply to the master and cast down.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..autograd import no_grad
from ..nn.clip import ClipGradBase
from ..nn.layer.layers import Parameter
from ..tensor.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError("parameters must be provided (eager mode)")
        self._param_groups = []
        params = list(parameters)
        if params and isinstance(params[0], dict):
            for g in params:
                group = dict(g)
                group["params"] = list(group["params"])
                self._param_groups.append(group)
        else:
            self._param_groups.append({"params": params})
        self._parameter_list = [p for g in self._param_groups for p in g["params"]]
        self._learning_rate = learning_rate
        self._weight_decay = self._wd_value(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._master_weights: Dict[str, jnp.ndarray] = {}
        self._row_masks: Dict[str, jnp.ndarray] = {}
        self._step_count = 0

    @staticmethod
    def _wd_value(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L2Decay-style objects expose .coeff in the reference
        return float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def _create_accumulators(self, param: Parameter) -> Dict[str, jnp.ndarray]:
        raise NotImplementedError

    def set_param_row_mask(self, param: Parameter, mask):
        """Restrict the next ``step()``s to the ACTIVE leading rows of one
        parameter (PR 10: active-only expert optimizer state).

        ``mask`` is a bool array broadcastable over ``param``'s leading
        dims (e.g. [E] for a stacked [E, H, I] expert weight, from the
        ``moe_expert_rows`` routing stats). False rows are frozen by
        SELECT: the param and every same-shaped accumulator (moments,
        velocity, ...) pass through bitwise-unchanged — no decay, no
        read-modify-write arithmetic — while True rows are bitwise-
        identical to the unmasked update (lazy/sparse-Adam semantics;
        scalar state like the beta powers still advances globally).
        Pass ``None`` to clear. The mask persists until replaced, so
        per-step callers should refresh it from each step's stats."""
        key = param.name if hasattr(param, "name") else str(param)
        if mask is None:
            self._row_masks.pop(key, None)
        else:
            self._row_masks[key] = jnp.asarray(mask, bool)

    def _update(self, p, g, state, lr, wd, group):
        """Pure update rule on arrays. Returns (new_p, new_state)."""
        raise NotImplementedError

    # -- main entry points -------------------------------------------------
    @no_grad()
    def step(self):
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        clipped = {id(p): g for p, g in params_grads}
        self._step_count += 1
        for group in self._param_groups:
            lr_scale = group.get("learning_rate", 1.0)
            wd = self._wd_value(group.get("weight_decay", None)) \
                if "weight_decay" in group else self._weight_decay
            lr = self.get_lr() * lr_scale
            for p in group["params"]:
                g = clipped.get(id(p))
                if g is None:
                    continue
                self._apply_one(p, g._data if isinstance(g, Tensor) else g,
                                lr, wd, group)

    def _apply_one(self, p: Parameter, g, lr, wd, group):
        key = p.name
        state = self._accumulators.get(key)
        if state is None:
            state = self._create_accumulators(p)
            self._accumulators[key] = state
        compute_p = p._data
        master = None
        if self._multi_precision and p._data.dtype in (jnp.bfloat16, jnp.float16):
            master = self._master_weights.get(key)
            if master is None:
                master = p._data.astype(jnp.float32)
            compute_p = master
        g = g.astype(compute_p.dtype)
        # per-parameter learning rate from ParamAttr
        lr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
        new_p, new_state = self._update(compute_p, g, state, lr, wd, group)
        mask = self._row_masks.get(key)
        if mask is not None:
            keep = mask.reshape(mask.shape + (1,) * (new_p.ndim - mask.ndim))
            # select (not multiply): frozen rows keep their exact bits in
            # the param AND every same-shaped accumulator; scalar state
            # (beta powers, step counts) advances globally
            new_p = jnp.where(keep, new_p, compute_p)
            new_state = {
                n: (jnp.where(keep, v, state[n]).astype(v.dtype)
                    if hasattr(v, "shape") and v.shape == new_p.shape else v)
                for n, v in new_state.items()}
        if master is not None:
            self._master_weights[key] = new_p
            p._data = new_p.astype(p._data.dtype)
        else:
            p._data = new_p
        self._accumulators[key] = new_state

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        sd = {"step_count": self._step_count, "accumulators": {}, "master_weights": {}}
        for k, st in self._accumulators.items():
            sd["accumulators"][k] = {n: Tensor._from_data(v) if hasattr(v, "shape") else v
                                     for n, v in st.items()}
        for k, v in self._master_weights.items():
            sd["master_weights"][k] = Tensor._from_data(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state):
        self._step_count = state.get("step_count", 0)
        for k, st in state.get("accumulators", {}).items():
            self._accumulators[k] = {
                n: (v._data if isinstance(v, Tensor) else v) for n, v in st.items()}
        for k, v in state.get("master_weights", {}).items():
            self._master_weights[k] = v._data if isinstance(v, Tensor) else v
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    load_state_dict = set_state_dict
