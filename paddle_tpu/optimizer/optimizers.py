"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,adamw,...}.py).

Update rules are pure jnp functions jit-cached per parameter shape; states are
fp32 regardless of param dtype (bf16-safe, like the reference's
multi-precision kernels). Adam/AdamW expose the reference's
``multi_precision`` knob directly (default True = f32 moments + master
weights; False narrows the stored moments to the param dtype, halving
optimizer HBM streaming on bf16 stacks — update math stays f32).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .optimizer import Optimizer


def _f32(x):
    return x.astype(jnp.float32)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _create_accumulators(self, p):
        return {}

    def _update(self, p, g, state, lr, wd, group):
        if wd:
            g = g + wd * p
        return (p - lr * g).astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_accumulators(self, p):
        return {"velocity": jnp.zeros(p._data.shape, jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        if wd:
            g = g + wd * p
        v = self._momentum * state["velocity"] + _f32(g)
        if self._nesterov:
            upd = _f32(g) + self._momentum * v
        else:
            upd = v
        return (p - lr * upd.astype(p.dtype)).astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    """multi_precision (reference adam kernels' MultiPrecision attr)
    defaults to TRUE here: f32 moments regardless of param dtype plus f32
    master weights for bf16/fp16 params. multi_precision=False stores
    the moments in each PARAM's dtype — half the optimizer HBM traffic
    on a bf16 stack; the update still computes in f32 and only the
    stored state narrows (update-parity test-asserted).

    ``set_param_row_mask`` (base class, PR 10) composes with both knobs:
    on a stacked expert weight it freezes the moment read-modify-write
    for experts with zero routed tokens this step — frozen moments are
    bitwise-unchanged (NOT decayed: lazy/sparse-Adam semantics) and
    touched experts are bitwise-identical to the unmasked update."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None,
                 multi_precision=True, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad
        self._decoupled_wd = False  # Adam applies wd as L2 into grad

    def _create_accumulators(self, p):
        mdt = jnp.float32 if self._multi_precision else p._data.dtype
        st = {"moment1": jnp.zeros(p._data.shape, mdt),
              "moment2": jnp.zeros(p._data.shape, mdt),
              "beta1_pow": jnp.ones((), jnp.float32),
              "beta2_pow": jnp.ones((), jnp.float32)}
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros(p._data.shape, mdt)
        return st

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        p32 = _f32(p)
        if wd and not self._decoupled_wd:
            g32 = g32 + wd * p32
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        # math in f32 always; storage follows the accumulator dtype (f32
        # under multi_precision — a no-op cast, bit-identical to before)
        mdt = state["moment1"].dtype
        m = b1 * _f32(state["moment1"]) + (1 - b1) * g32
        v = b2 * _f32(state["moment2"]) + (1 - b2) * g32 * g32
        m_hat = m / (1 - b1p)
        if self._amsgrad:
            v_max = jnp.maximum(_f32(state["moment2_max"]), v)
            v_hat = v_max / (1 - b2p)
            new_state = {"moment1": m.astype(mdt), "moment2": v.astype(mdt),
                         "moment2_max": v_max.astype(mdt),
                         "beta1_pow": b1p, "beta2_pow": b2p}
        else:
            v_hat = v / (1 - b2p)
            new_state = {"moment1": m.astype(mdt), "moment2": v.astype(mdt),
                         "beta1_pow": b1p, "beta2_pow": b2p}
        upd = m_hat / (jnp.sqrt(v_hat) + self._eps)
        if wd and self._decoupled_wd:
            upd = upd + wd * p32
        return (p32 - lr * upd).astype(p.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py).
    Shares Adam's ``multi_precision`` moment-dtype knob (default True:
    f32 moments)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, name,
                         multi_precision, amsgrad)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_one(self, p, g, lr, wd, group):
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            wd = 0.0
        super()._apply_one(p, g, lr, wd, group)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, p):
        return {"moment": jnp.full(p._data.shape, self._init_acc, jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        acc = state["moment"] + g32 * g32
        new_p = _f32(p) - lr * g32 / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(p.dtype), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, p):
        return {"mean_square": jnp.zeros(p._data.shape, jnp.float32),
                "mean_grad": jnp.zeros(p._data.shape, jnp.float32),
                "momentum": jnp.zeros(p._data.shape, jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g32 * g32
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        return (_f32(p) - mom).astype(p.dtype), \
            {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _create_accumulators(self, p):
        return {"moment": jnp.zeros(p._data.shape, jnp.float32),
                "inf_norm": jnp.zeros(p._data.shape, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        b1p = state["beta1_pow"] * self._beta1
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        new_p = _f32(p) - (lr / (1 - b1p)) * m / (u + self._eps)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (ref: python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_accumulators(self, p):
        return {"moment1": jnp.zeros(p._data.shape, jnp.float32),
                "moment2": jnp.zeros(p._data.shape, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _apply_one(self, p, g, lr, wd, group):
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        super()._apply_one(p, g, lr, wd, group)

    def _update(self, p, g, state, lr, wd, group):
        g32, p32 = _f32(g), _f32(p)
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * g32 * g32
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self._eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p32 - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v,
                                       "beta1_pow": b1p, "beta2_pow": b2p}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps, self._rho = epsilon, rho

    def _create_accumulators(self, p):
        return {"avg_squared_grad": jnp.zeros(p._data.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p._data.shape, jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g32 * g32
        upd = g32 * jnp.sqrt(state["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * upd * upd
        return (_f32(p) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class LarsMomentum(Optimizer):
    """LARS (ref: incubate LarsMomentumOptimizer / lars_momentum op):
    layer-wise adaptive rate — local_lr = lr * lars_coeff * ||w|| /
    (||g|| + lars_weight_decay * ||w||), then momentum on the scaled grad.
    Used for large-batch vision training (the reference's ResNet configs)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=1e-9, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _create_accumulators(self, p):
        return {"velocity": jnp.zeros(p._data.shape, jnp.float32)}

    def _apply_one(self, p, g, lr, wd, group):
        # excluded params (by name substring, reference semantics: BN/bias)
        # get plain momentum: no lars decay, no adaptive scaling
        if any(tok in p.name for tok in self._exclude):
            group = dict(group or {}, lars_excluded=True)
        super()._apply_one(p, g, lr, wd, group)

    def _update(self, p, g, state, lr, wd, group):
        g32, p32 = _f32(g), _f32(p)
        if (group or {}).get("lars_excluded"):
            v = self._momentum * state["velocity"] + lr * g32
            return (p32 - v).astype(p.dtype), {"velocity": v}
        wnorm = jnp.sqrt(jnp.sum(p32 * p32))
        gnorm = jnp.sqrt(jnp.sum(g32 * g32))
        lars_wd = self._lars_wd
        local = jnp.where(
            (wnorm > 0) & (gnorm > 0),
            lr * self._lars_coeff * wnorm
            / (gnorm + lars_wd * wnorm + self._eps),
            lr)
        v = self._momentum * state["velocity"] + local * (g32 + lars_wd * p32)
        return (p32 - v).astype(p.dtype), {"velocity": v}


class Rprop(Optimizer):
    """Resilient backprop (ref: paddle.optimizer.Rprop): per-element step
    sizes grow when the gradient keeps its sign and shrink when it flips;
    only the SIGN of the gradient is used."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _create_accumulators(self, p):
        return {"prev_grad": jnp.zeros(p._data.shape, jnp.float32),
                "step": jnp.full(p._data.shape, float(self.get_lr()),
                                 jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        sign = jnp.sign(g32 * state["prev_grad"])
        step = jnp.clip(
            jnp.where(sign > 0, state["step"] * self._eta_pos,
                      jnp.where(sign < 0, state["step"] * self._eta_neg,
                                state["step"])),
            self._lr_min, self._lr_max)
        # on a sign flip the pending update is skipped and the stored
        # gradient zeroed (classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, g32)
        new_p = _f32(p) - jnp.sign(g_eff) * step
        return new_p.astype(p.dtype), {"prev_grad": g_eff, "step": step}


class ASGD(Optimizer):
    """Averaged SGD (ref: paddle.optimizer.ASGD): plain SGD steps plus a
    running average of the iterates; read it with ``averaged(param)``
    (e.g. to evaluate with the Polyak-averaged weights)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        # the reference smooths grads over batch_num batches; with the
        # whole batch's grad available per step this is a 1-step window
        self._batch_num = max(int(batch_num), 1)

    def _create_accumulators(self, p):
        return {"avg": _f32(p), "t": jnp.zeros((), jnp.float32)}

    def averaged(self, p):
        """The running average of `p`'s iterates (zeros-state params that
        never stepped return the current value)."""
        state = self._accumulators.get(p.name)
        if state is None:
            return p
        from ..tensor.tensor import Tensor
        return Tensor(state["avg"].astype(p._data.dtype))

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        new_p = _f32(p) - lr * g32
        t = state["t"] + 1
        avg = state["avg"] + (new_p - state["avg"]) / t
        return new_p.astype(p.dtype), {"avg": avg, "t": t}


class NAdam(Optimizer):
    """Adam with Nesterov momentum (ref: paddle.optimizer.NAdam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _create_accumulators(self, p):
        return {"m": jnp.zeros(p._data.shape, jnp.float32),
                "v": jnp.zeros(p._data.shape, jnp.float32),
                "t": jnp.zeros((), jnp.float32),
                "mu_prod": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        t = state["t"] + 1
        mu_t = self._b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = self._b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = state["mu_prod"] * mu_t
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * g32 * g32
        m_hat = (mu_next * m / (1 - mu_prod * mu_next)
                 + (1 - mu_t) * g32 / (1 - mu_prod))
        v_hat = v / (1 - self._b2 ** t)
        new_p = _f32(p) - lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t,
                                       "mu_prod": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (ref: paddle.optimizer.RAdam): warms up the adaptive
    term by the variance-rectification factor; falls back to SGD-with-
    momentum while the variance estimate is untrustworthy."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon

    def _create_accumulators(self, p):
        return {"m": jnp.zeros(p._data.shape, jnp.float32),
                "v": jnp.zeros(p._data.shape, jnp.float32),
                "t": jnp.zeros((), jnp.float32)}

    def _update(self, p, g, state, lr, wd, group):
        g32 = _f32(g)
        if wd:
            g32 = g32 + wd * _f32(p)
        t = state["t"] + 1
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * g32 * g32
        m_hat = m / (1 - self._b1 ** t)
        rho_inf = 2.0 / (1 - self._b2) - 1
        rho_t = rho_inf - 2 * t * self._b2 ** t / (1 - self._b2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                     / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                   1e-12))
        v_hat = jnp.sqrt(v / (1 - self._b2 ** t))
        adaptive = lr * r * m_hat / (v_hat + self._eps)
        plain = lr * m_hat
        new_p = _f32(p) - jnp.where(rho_t > 4.0, adaptive, plain)
        return new_p.astype(p.dtype), {"m": m, "v": v, "t": t}
