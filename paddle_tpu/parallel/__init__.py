"""Functional SPMD parallel engine: the mesh-level building blocks the fleet
API surface is implemented on (pipeline, ring attention, MoE dispatch, FSDP
sharding rules). Everything here is pure jax — shard_map/pjit-composable."""
from .pipeline import (build_pipeline_loss_fn, last_stage_value, microbatch,
                       pipeline_apply, stack_stage_params)
from .ring_attention import ring_attention
from .ulysses_attention import (ENV_SEP_STRATEGY, SEP_STRATEGIES,
                                resolve_sep_strategy, ulysses_attention)
from .moe import moe_dispatch_combine
