"""Decomposed collective matmuls for tensor parallelism ("collective matmul").

The blocking TP path lets GSPMD emit one fused collective around each sharded
matmul: row-parallel is matmul -> all-reduce, column-parallel (gathered) is
matmul -> all-gather, both sitting as barriers on the critical path. Here each
fused collective is decomposed into a ``ppermute`` ring of partial matmuls so
every hop's transfer overlaps the next chunk's compute (Megatron / maxtext
style), inside a fully-manual shard_map island over the active mesh.

Chunked hops (mp>2): each of the n ring hops still moves one full shard per
``ppermute``, so at mp=4/8 the first hop exposes most of its transfer latency
before any partial matmul can consume it. ``resolve_chunks`` therefore splits
every hop into independent row sub-tiles (``PADDLE_TPU_TP_OVERLAP_CHUNKS``,
default auto: ~``min_chunk()`` rows per sub-tile) — disjoint row slices
ppermuted separately, so hop s's in-flight sub-tiles overlap hop s+1's
partial matmul instead of serializing whole shards. Sub-tiling only splits
transfer granularity (the adds stay elementwise on disjoint rows), so a
chunked ring is BITWISE identical to the unchunked ring; mp=2 always runs
unchunked (one transfer hop, nothing to split — and it is the bitwise parity
contract against blocking).

Numerics: the ring kernels carry a custom_vjp whose backward issues exactly
the same ops as the blocking path's backward, and at mp=2 the forward ring
reduction is a two-term sum (commutative in fp), so overlapped == blocking
bit-for-bit at mp=2; for mp>2 the all-reduce variant re-associates the
partial-sum order and matches to fp tolerance (the all-gather variant is
bitwise at any degree — it has no cross-rank reduction).

Beyond the Linear pair, the same ring machinery backs three more surfaces:
``plan_fused_ffn`` runs a column->act->row pair inside ONE island whose only
collective is the final chunked reduce ring (the intermediate activation is
never gathered); ``plan_vocab_parallel_embedding`` reduces the masked local
lookups of a vocab-sharded table over a ring (each row is non-zero on exactly
one rank, so the ring sum is exact in any dtype); and
``plan_parallel_cross_entropy`` ring-gathers per-rank (max, sumexp, picked)
stats — [n, t, 3] on the wire instead of replicated [t, V] logits.

Switches: ``PADDLE_TPU_TP_OVERLAP=1`` turns the overlap on;
``PADDLE_TPU_TP_OVERLAP_MIN_CHUNK`` (default 64) is the smallest per-step
chunk (ring rows / gathered columns) worth issuing — below it the partial
matmuls can't keep an MXU busy and the fused collective wins, so the layer
falls back. Fallback is also automatic when mp == 1, no mesh is active, or
the shapes don't divide the ring. Plans are memoized per (shapes, mesh,
kwargs, overlap env) so layer forwards don't rebuild the shard_map island —
or re-bump the ``tp.*.plans`` counters — on every call.
"""
from __future__ import annotations

import collections
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import envs
from .._compat import shard_map
from ..observability import trace as _obs

ENV_OVERLAP = "PADDLE_TPU_TP_OVERLAP"
ENV_MIN_CHUNK = "PADDLE_TPU_TP_OVERLAP_MIN_CHUNK"
ENV_CHUNKS = "PADDLE_TPU_TP_OVERLAP_CHUNKS"
_DEFAULT_MIN_CHUNK = 64


def overlap_enabled() -> bool:
    return envs.get(ENV_OVERLAP)


def min_chunk() -> int:
    return envs.get(ENV_MIN_CHUNK)


def overlap_chunks():
    """Explicit per-hop sub-tile count from PADDLE_TPU_TP_OVERLAP_CHUNKS,
    or None for auto (target ~min_chunk() rows per sub-tile)."""
    return envs.get(ENV_CHUNKS)


def resolve_chunks(n: int, rows: int) -> int:
    """Sub-tiles per ring hop for a hop payload of ``rows`` rows.

    mp<=2 stays unchunked: a 2-ring has a single transfer hop per phase and
    is the bitwise-vs-blocking parity contract, so there is nothing to
    pipeline. An explicit PADDLE_TPU_TP_OVERLAP_CHUNKS wins when it divides
    the hop rows (falling back to unchunked when it doesn't — never a
    ragged sub-tile); auto targets ~min_chunk() rows per sub-tile, snapped
    down to the nearest divisor of ``rows``.
    """
    if n <= 2 or rows <= 1:
        return 1
    req = overlap_chunks()
    if req is not None:
        return req if (req <= rows and rows % req == 0) else 1
    k = max(1, min(rows, rows // max(1, min_chunk())))
    while rows % k:
        k -= 1
    return k


# ---------------------------------------------------------------------------
# ring kernels (called INSIDE a fully-manual shard_map over the mesh)
# ---------------------------------------------------------------------------

def _ring_hop(buf, axis_name, perm, nchunks, span):
    """One ring hop, split into ``nchunks`` independent row sub-tile
    ppermutes. The sub-tiles are disjoint row slices reassembled by concat,
    so chunked == unchunked bitwise; each sub-tile is its own
    collective-permute in the HLO, free to be scheduled (and its latency
    hidden) independently of its siblings."""
    if nchunks <= 1:
        with _obs.comm_span(span, nbytes=buf.size * buf.dtype.itemsize,
                            site="tp_ring.hop"):
            return lax.ppermute(buf, axis_name, perm)
    rc = buf.shape[0] // nchunks
    tiles = []
    for j in range(nchunks):
        t = lax.slice_in_dim(buf, j * rc, (j + 1) * rc, axis=0)
        with _obs.comm_span(span, nbytes=t.size * t.dtype.itemsize,
                            site="tp_ring.hop"):
            tiles.append(lax.ppermute(t, axis_name, perm))
    return jnp.concatenate(tiles, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def ring_allreduce_matmul(x, w, n, axis_name, nchunks=1):
    """Row-parallel matmul with the all-reduce decomposed into a ring.

    x: [t, k/n] local rows (full t), w: [k/n, out] local shard ->
    [t, out] fully reduced, identical on every rank along ``axis_name``.

    Reduce-scatter ring: at step s rank r multiplies its row chunk
    c = (r - s - 1) % n and adds it onto the accumulator arriving from rank
    r-1 (which computed the same chunk's partial last step) — the constraint
    c_s(r) = c_{s-1}(r-1) pins the schedule. After n steps rank r holds row
    chunk r fully reduced; a ring all-gather reassembles [t, out]. Each
    ppermute overlaps the next chunk's partial matmul, and at ``nchunks`` > 1
    every hop is further split into row sub-tiles (bitwise-neutral; see
    ``_ring_hop``).
    """
    r = lax.axis_index(axis_name)
    t = x.shape[0]
    tc = t // n
    fwd = [(i, (i + 1) % n) for i in range(n)]
    acc = None
    for s in range(n):
        if s > 0:
            acc = _ring_hop(acc, axis_name, fwd, nchunks,
                            "tp_ring_allreduce.hop")
        c = (r - s - 1) % n
        rows = lax.dynamic_slice_in_dim(x, c * tc, tc, 0)
        with jax.named_scope("tp_ring_allreduce.partial_matmul"):
            part = rows @ w
        acc = part if acc is None else acc + part
    out = jnp.zeros((t,) + acc.shape[1:], acc.dtype)
    out = lax.dynamic_update_slice_in_dim(out, acc, r * tc, 0)
    buf = acc
    for h in range(1, n):
        buf = _ring_hop(buf, axis_name, fwd, nchunks,
                        "tp_ring_allreduce.gather_hop")
        out = lax.dynamic_update_slice_in_dim(out, buf, ((r - h) % n) * tc, 0)
    return out


def _rar_fwd(x, w, n, axis_name, nchunks=1):
    return ring_allreduce_matmul(x, w, n, axis_name, nchunks), (x, w)


def _rar_bwd(n, axis_name, nchunks, res, g):
    # shard_map (check_rep/vma off) hands an mp-replicated output's cotangent
    # back DIVIDED by the mp size; the blocking psum(x @ w) backward restores
    # it through its psum transpose. Issue the identical psum so both paths
    # run the same ops bitwise, then both grads are local matmuls.
    x, w = res
    g = lax.psum(g, axis_name)
    return g @ w.T, x.T @ g


ring_allreduce_matmul.defvjp(_rar_fwd, _rar_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def ring_allgather_matmul(x, w, n, axis_name, nchunks=1):
    """Column-parallel matmul with the output all-gather decomposed into a
    chunked pipeline.

    x: [t, k] replicated, w: [k, out/n] local shard -> [t, out] gathered.

    The local column block is computed in n row chunks; as soon as chunk c's
    [t/n, out/n] block is done it starts riding the ring (n-1 hops to reach
    everyone) while chunk c+1's matmul runs — the hops carry no data
    dependence on later chunks, so the scheduler overlaps transfer with
    compute. At ``nchunks`` > 1 each hop additionally moves independent row
    sub-tiles. Per-device FLOPs and bytes moved are identical to the fused
    path, and every output element is produced by the same x @ w_shard
    product on its owning rank, so the result is bitwise identical to
    matmul + all-gather at ANY degree (chunked or not).
    """
    r = lax.axis_index(axis_name)
    t = x.shape[0]
    tc = t // n
    nc = w.shape[1]
    fwd = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((t, nc * n), jnp.result_type(x.dtype, w.dtype))
    for c in range(n):
        rows = lax.dynamic_slice_in_dim(x, c * tc, tc, 0)
        with jax.named_scope("tp_ring_allgather.partial_matmul"):
            buf = rows @ w
        row0 = jnp.asarray(c * tc, r.dtype)
        out = lax.dynamic_update_slice(out, buf, (row0, r * nc))
        for h in range(1, n):
            buf = _ring_hop(buf, axis_name, fwd, nchunks,
                            "tp_ring_allgather.hop")
            out = lax.dynamic_update_slice(
                out, buf, (row0, ((r - h) % n) * nc))
    return out


def _rag_fwd(x, w, n, axis_name, nchunks=1):
    return ring_allgather_matmul(x, w, n, axis_name, nchunks), (x, w)


def _rag_bwd(n, axis_name, nchunks, res, g):
    # blocking backward of all_gather(x @ w, tiled): the gather transpose is a
    # psum_scatter — psum the (1/n-scaled, see _rar_bwd) cotangent and slice
    # the rank's own column block. dx stays per-rank partial; the shard_map
    # boundary transpose psums it over mp (x is unmentioned there), exactly as
    # it does for the blocking path.
    x, w = res
    r = lax.axis_index(axis_name)
    nc = w.shape[1]
    g_loc = lax.dynamic_slice_in_dim(lax.psum(g, axis_name), r * nc, nc, 1)
    dx = g_loc @ w.T
    dw = x.T @ g_loc
    return dx, dw


ring_allgather_matmul.defvjp(_rag_fwd, _rag_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def ring_allreduce(x, n, axis_name, nchunks=1):
    """Plain all-reduce of x [t, ...] decomposed into the same
    reduce-scatter ring + gather ring as ``ring_allreduce_matmul``, minus
    the matmul — the reduce surface for non-matmul partials (e.g. the
    vocab-parallel embedding's masked local lookups). Re-associates the
    partial-sum order like any ring (fp tolerance at n>2), EXCEPT when the
    cross-rank addends are disjoint (at most one non-zero contribution per
    element), where the sum is exact in any dtype and any order."""
    r = lax.axis_index(axis_name)
    t = x.shape[0]
    tc = t // n
    fwd = [(i, (i + 1) % n) for i in range(n)]
    acc = None
    for s in range(n):
        if s > 0:
            acc = _ring_hop(acc, axis_name, fwd, nchunks,
                            "ring_allreduce.hop")
        c = (r - s - 1) % n
        part = lax.dynamic_slice_in_dim(x, c * tc, tc, 0)
        acc = part if acc is None else acc + part
    out = jnp.zeros_like(x)
    out = lax.dynamic_update_slice_in_dim(out, acc, r * tc, 0)
    buf = acc
    for h in range(1, n):
        buf = _ring_hop(buf, axis_name, fwd, nchunks,
                        "ring_allreduce.gather_hop")
        out = lax.dynamic_update_slice_in_dim(out, buf, ((r - h) % n) * tc, 0)
    return out


def _rr_fwd(x, n, axis_name, nchunks=1):
    return ring_allreduce(x, n, axis_name, nchunks), None


def _rr_bwd(n, axis_name, nchunks, res, g):
    # replicated-output cotangent arrives 1/n-scaled (see _rar_bwd); the
    # blocking psum's transpose is the same psum
    return (lax.psum(g, axis_name),)


ring_allreduce.defvjp(_rr_fwd, _rr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def ring_allgather(x, n, axis_name, nchunks=1):
    """all_gather of x (stacked on a NEW leading axis: [n, ...]) decomposed
    into a ppermute ring. No cross-rank reduction, so bitwise identical to
    the fused all_gather at any degree, chunked or not."""
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    zeros = (jnp.zeros((), r.dtype),) * x.ndim
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_slice(out, x[None], (r,) + zeros)
    buf = x
    for h in range(1, n):
        buf = _ring_hop(buf, axis_name, fwd, nchunks, "ring_allgather.hop")
        out = lax.dynamic_update_slice(
            out, buf[None], (jnp.asarray((r - h) % n, r.dtype),) + zeros)
    return out


def _rg_fwd(x, n, axis_name, nchunks=1):
    return ring_allgather(x, n, axis_name, nchunks), None


def _rg_bwd(n, axis_name, nchunks, res, g):
    # blocking all_gather transpose: psum the (1/n-scaled) [n, ...]
    # cotangent and take the rank's own slab — same ops as the fused path.
    r = lax.axis_index(axis_name)
    return (lax.dynamic_index_in_dim(lax.psum(g, axis_name), r, 0,
                                     keepdims=False),)


ring_allgather.defvjp(_rg_fwd, _rg_bwd)


# blocking references (same island layout, fused collective) — the parity
# baseline the ring kernels must match bit-for-bit at degree 2
def blocking_allreduce_matmul(x, w, n, axis_name):
    y = x @ w
    with _obs.comm_span("tp_blocking.allreduce",
                        nbytes=y.size * y.dtype.itemsize,
                        site="tp_blocking.allreduce"):
        return lax.psum(y, axis_name)


def blocking_allgather_matmul(x, w, n, axis_name):
    y = x @ w
    with _obs.comm_span("tp_blocking.allgather",
                        nbytes=y.size * y.dtype.itemsize,
                        site="tp_blocking.allgather"):
        return lax.all_gather(y, axis_name, axis=1, tiled=True)


# named activations for plan_fused_ffn — module-level defs (stable object
# identity) so memoized plans keyed on the callable actually hit
def swiglu(g, u):
    """Llama MLP gate: silu(gate) * up."""
    return jax.nn.silu(g) * u


def gelu_tanh(h):
    """GPT-2 MLP activation — tanh-approximate gelu, the same jax.nn op
    F.gelu(approximate=True) lowers to."""
    return jax.nn.gelu(h, approximate=True)


# ---------------------------------------------------------------------------
# GSPMD embedding: fully-manual islands callable from hint-traced layer code
# ---------------------------------------------------------------------------

def _batch_axis_spec(mesh, t, batch_axis):
    """Shard the flattened token dim over ``batch_axis`` (an axis name or a
    tuple of axis names) when the product of present axis sizes divides
    cleanly (keeps a dp-sharded batch in place); replicate otherwise."""
    if not batch_axis:
        return None
    axes = (batch_axis,) if isinstance(batch_axis, str) else tuple(batch_axis)
    axes = tuple(ax for ax in axes
                 if ax in mesh.shape and mesh.shape[ax] > 1)
    if not axes:
        return None
    deg = 1
    for ax in axes:
        deg *= mesh.shape[ax]
    if t % deg:
        return None
    return axes[0] if len(axes) == 1 else axes


def _batch_degree(mesh, bax):
    if bax is None:
        return 1
    axes = (bax,) if isinstance(bax, str) else tuple(bax)
    deg = 1
    for ax in axes:
        deg *= mesh.shape[ax]
    return deg


def _island(mesh, body, n, mp_axis, x_spec, w_spec, out_spec):
    return shard_map(functools.partial(body, n=n, axis_name=mp_axis),
                     mesh=mesh, in_specs=(x_spec, w_spec),
                     out_specs=out_spec, axis_names=frozenset(mesh.axis_names),
                     check_vma=False)


# --- plan memoization -------------------------------------------------------
# Every parallel layer used to call plan_* on EVERY forward, rebuilding the
# shard_map island (a new traced callable per call — defeating jit caching of
# anything keyed on it) and re-bumping the tp.*.plans counters. Plans are
# pure functions of (shapes, mesh, kwargs) plus the overlap env knobs, so
# they memoize cleanly; the env values join the key so tests (and users)
# flipping PADDLE_TPU_TP_OVERLAP_* between calls still get fresh plans.

_PLAN_CACHE = collections.OrderedDict()
_PLAN_CACHE_MAX = 256


def clear_plan_cache():
    _PLAN_CACHE.clear()


def _memoized_plan(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        key = (fn.__name__, args, tuple(sorted(kwargs.items())),
               envs.raw(ENV_MIN_CHUNK), envs.raw(ENV_CHUNKS))
        try:
            hash(key)
        except TypeError:
            return fn(*args, **kwargs)  # unhashable arg: build unmemoized
        if key in _PLAN_CACHE:
            _PLAN_CACHE.move_to_end(key)
            return _PLAN_CACHE[key]
        plan = fn(*args, **kwargs)
        _PLAN_CACHE[key] = plan
        if len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.popitem(last=False)
        return plan
    return wrapper


@_memoized_plan
def plan_row_parallel(x_shape, w_shape, mesh, mp_axis="mp", batch_axis="dp",
                      kernel=ring_allreduce_matmul):
    """Overlapped row-parallel linear: x [..., k] (k sharded over mp),
    w [k, out] -> [..., out] replicated over mp. Returns an apply(x, w)
    closure, or None when the overlap doesn't apply (caller falls back to
    the fused GSPMD path)."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    k, out_f = w_shape
    if x_shape[-1] != k or k % n:
        return None
    t = 1
    for d in x_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // _batch_degree(mesh, bax)
    # ring chunks are rows of the LOCAL token block
    if t_loc % n or t_loc // n < min_chunk():
        return None
    nchunks = resolve_chunks(n, t_loc // n)
    f = _island(mesh, functools.partial(kernel, nchunks=nchunks), n, mp_axis,
                P(bax, mp_axis), P(mp_axis, None), P(bax, None))
    _obs.record_counter("tp.row_parallel.plans")

    def apply(x, w):
        out = f(x.reshape(t, k), w)
        return out.reshape(tuple(x_shape[:-1]) + (out_f,))

    return apply


@_memoized_plan
def plan_column_parallel(x_shape, w_shape, mesh, mp_axis="mp",
                         batch_axis="dp", kernel=ring_allgather_matmul):
    """Overlapped column-parallel linear with gathered output: x [..., k]
    replicated, w [k, out] (out sharded over mp) -> [..., out] gathered.
    Returns an apply(x, w) closure, or None when the overlap doesn't apply."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    k, out_f = w_shape
    if x_shape[-1] != k or out_f % n or out_f // n < min_chunk():
        return None
    t = 1
    for d in x_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // _batch_degree(mesh, bax)
    # pipeline chunks are row blocks of the LOCAL token dim
    if t_loc % n or t_loc // n < min_chunk():
        return None
    nchunks = resolve_chunks(n, t_loc // n)
    f = _island(mesh, functools.partial(kernel, nchunks=nchunks), n, mp_axis,
                P(bax, None), P(None, mp_axis), P(bax, None))
    _obs.record_counter("tp.column_parallel.plans")

    def apply(x, w):
        out = f(x.reshape(t, k), w)
        return out.reshape(tuple(x_shape[:-1]) + (out_f,))

    return apply


@_memoized_plan
def plan_fused_ffn(x_shape, col_shape, row_shape, mesh, n_cols=1,
                   mp_axis="mp", batch_axis="dp", activation=gelu_tanh,
                   col_bias=False):
    """Fused column->activation->row pair inside ONE island that skips the
    intermediate gather: x [..., k] replicated; ``n_cols`` column weights
    [k, i] (i sharded over mp); row weight [i, out] (i sharded over mp) ->
    [..., out] reduced over mp. The local column matmuls and the activation
    run entirely on the [t, i/n] shard — the only collective is the row
    matmul's chunked reduce-scatter/gather ring, so the [t, i] activation
    never rides the wire at all (the unfused pair gathers it or re-enters
    GSPMD between the layers). Returns apply(x, w_cols, w_row, b_cols), or
    None when the overlap doesn't apply."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    k, inter = col_shape
    inter2, out_f = row_shape
    if x_shape[-1] != k or inter2 != inter:
        return None
    if inter % n or inter // n < min_chunk():
        return None
    t = 1
    for d in x_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // _batch_degree(mesh, bax)
    if t_loc % n or t_loc // n < min_chunk():
        return None
    nchunks = resolve_chunks(n, t_loc // n)

    def body(x, w_cols, w_row, b_cols):
        with jax.named_scope("tp_fused_ffn.column_matmul"):
            hs = [x @ w for w in w_cols]
            if b_cols:
                hs = [h + b for h, b in zip(hs, b_cols)]
            h = activation(*hs)
        return ring_allreduce_matmul(h, w_row, n, mp_axis, nchunks)

    col_specs = (P(None, mp_axis),) * n_cols
    bias_specs = (P(mp_axis),) * n_cols if col_bias else ()
    f = shard_map(body, mesh=mesh,
                  in_specs=(P(bax, None), col_specs, P(mp_axis, None),
                            bias_specs),
                  out_specs=P(bax, None),
                  axis_names=frozenset(mesh.axis_names), check_vma=False)
    _obs.record_counter("tp.fused_ffn.plans")

    def apply(x, w_cols, w_row, b_cols=()):
        out = f(x.reshape(t, k), tuple(w_cols), w_row, tuple(b_cols))
        return out.reshape(tuple(x_shape[:-1]) + (out_f,))

    return apply


@_memoized_plan
def plan_vocab_parallel_embedding(ids_shape, table_shape, mesh, mp_axis="mp",
                                  batch_axis="dp"):
    """Ring-decomposed vocab-parallel embedding: table [V, H] with V sharded
    over mp, ids [...] -> [..., H] replicated over mp. Each rank looks up
    only the ids landing in its vocab slice (masked local gather) and the
    partial rows ride the chunked reduce ring. Every (b, s) row is non-zero
    on exactly ONE rank, so the ring sum is exact in any dtype and any
    association — bitwise against the fused psum. Returns apply(ids, table)
    or None when the overlap doesn't apply."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    V, H = table_shape
    if V % n:
        return None
    t = 1
    for d in ids_shape:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // _batch_degree(mesh, bax)
    if t_loc % n or t_loc // n < min_chunk():
        return None
    nchunks = resolve_chunks(n, t_loc // n)
    vs = V // n

    def body(ids, table):
        r = lax.axis_index(mp_axis)
        loc = ids.astype(jnp.int32) - r * vs
        ok = (loc >= 0) & (loc < vs)
        with jax.named_scope("vocab_embed.local_lookup"):
            rows = jnp.take(table, jnp.where(ok, loc, jnp.int32(0)), axis=0)
            part = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
        return ring_allreduce(part, n, mp_axis, nchunks)

    f = shard_map(body, mesh=mesh, in_specs=(P(bax), P(mp_axis, None)),
                  out_specs=P(bax, None),
                  axis_names=frozenset(mesh.axis_names), check_vma=False)
    _obs.record_counter("tp.vocab_embed.plans")

    def apply(ids, table):
        out = f(ids.reshape(t), table)
        return out.reshape(tuple(ids_shape) + (H,))

    return apply


@_memoized_plan
def plan_parallel_cross_entropy(logits_shape, mesh, mp_axis="mp",
                                batch_axis="dp"):
    """Ring-decomposed softmax CE over mp-sharded logits: per-rank partial
    (max, sumexp, picked-logit) stats ride a chunked ring all-gather —
    [n, t, 3] fp32 on the wire instead of the [t, V] logits the blocking
    logsumexp replicates through its psum — and every rank combines the
    gathered stats identically (fixed rank order, so the result is
    rank-independent; vs blocking it matches to fp tolerance, the log-sum
    is re-associated). The picked logit lives on exactly one rank (zero
    elsewhere), so its gathered sum is exact. Returns apply(logits, labels)
    -> [t] loss (no ignore_index masking — the caller masks), or None when
    the overlap doesn't apply."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    V = logits_shape[-1]
    if V % n or V // n < min_chunk():
        return None
    t = 1
    for d in logits_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // _batch_degree(mesh, bax)
    if t_loc < 1:
        return None
    nchunks = resolve_chunks(n, t_loc)
    vs = V // n

    def body(logits, labels):
        r = lax.axis_index(mp_axis)
        l32 = logits.astype(jnp.float32)
        with jax.named_scope("parallel_ce.local_stats"):
            m = jnp.max(l32, axis=-1)
            s = jnp.sum(jnp.exp(l32 - m[..., None]), axis=-1)
            loc = labels.astype(jnp.int32) - r * vs
            ok = (loc >= 0) & (loc < vs)
            picked = jnp.where(
                ok,
                jnp.take_along_axis(
                    l32, jnp.where(ok, loc, jnp.int32(0))[..., None],
                    axis=-1)[..., 0],
                jnp.float32(0.0))
            stats = jnp.stack([m, s, picked], axis=-1)  # [t, 3]
        allst = ring_allgather(stats, n, mp_axis, nchunks)  # [n, t, 3]
        with jax.named_scope("parallel_ce.combine"):
            ms, ss, ps = allst[..., 0], allst[..., 1], allst[..., 2]
            gm = jnp.max(ms, axis=0)
            lse = gm + jnp.log(jnp.sum(ss * jnp.exp(ms - gm), axis=0))
            return lse - jnp.sum(ps, axis=0)

    f = shard_map(body, mesh=mesh, in_specs=(P(bax, mp_axis), P(bax)),
                  out_specs=P(bax),
                  axis_names=frozenset(mesh.axis_names), check_vma=False)
    _obs.record_counter("tp.parallel_ce.plans")

    def apply(logits, labels):
        out = f(logits.reshape(t, V), labels.reshape(t).astype(jnp.int32))
        return out.reshape(tuple(logits_shape[:-1]))

    return apply


def overlap_row_parallel(x, w, mesh, **kwargs):
    plan = plan_row_parallel(x.shape, w.shape, mesh, **kwargs)
    return None if plan is None else plan(x, w)


def overlap_column_parallel(x, w, mesh, **kwargs):
    plan = plan_column_parallel(x.shape, w.shape, mesh, **kwargs)
    return None if plan is None else plan(x, w)
