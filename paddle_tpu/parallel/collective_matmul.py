"""Decomposed collective matmuls for tensor parallelism ("collective matmul").

The blocking TP path lets GSPMD emit one fused collective around each sharded
matmul: row-parallel is matmul -> all-reduce, column-parallel (gathered) is
matmul -> all-gather, both sitting as barriers on the critical path. Here each
fused collective is decomposed into a ``ppermute`` ring of partial matmuls so
every hop's transfer overlaps the next chunk's compute (Megatron / maxtext
style), inside a fully-manual shard_map island over the active mesh.

Numerics: the ring kernels carry a custom_vjp whose backward issues exactly
the same ops as the blocking path's backward, and at mp=2 the forward ring
reduction is a two-term sum (commutative in fp), so overlapped == blocking
bit-for-bit at mp=2; for mp>2 the all-reduce variant re-associates the
partial-sum order and matches to fp tolerance (the all-gather variant is
bitwise at any degree — it has no cross-rank reduction).

Switches: ``PADDLE_TPU_TP_OVERLAP=1`` turns the overlap on;
``PADDLE_TPU_TP_OVERLAP_MIN_CHUNK`` (default 64) is the smallest per-step
chunk (ring rows / gathered columns) worth issuing — below it the partial
matmuls can't keep an MXU busy and the fused collective wins, so the layer
falls back. Fallback is also automatic when mp == 1, no mesh is active, or
the shapes don't divide the ring.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .._compat import shard_map
from ..observability import trace as _obs

ENV_OVERLAP = "PADDLE_TPU_TP_OVERLAP"
ENV_MIN_CHUNK = "PADDLE_TPU_TP_OVERLAP_MIN_CHUNK"
_DEFAULT_MIN_CHUNK = 64


def overlap_enabled() -> bool:
    return os.environ.get(ENV_OVERLAP, "0").lower() in ("1", "true", "ring",
                                                        "on")


def min_chunk() -> int:
    return int(os.environ.get(ENV_MIN_CHUNK, _DEFAULT_MIN_CHUNK))


# ---------------------------------------------------------------------------
# ring kernels (called INSIDE a fully-manual shard_map over the mesh)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def ring_allreduce_matmul(x, w, n, axis_name):
    """Row-parallel matmul with the all-reduce decomposed into a ring.

    x: [t, k/n] local rows (full t), w: [k/n, out] local shard ->
    [t, out] fully reduced, identical on every rank along ``axis_name``.

    Reduce-scatter ring: at step s rank r multiplies its row chunk
    c = (r - s - 1) % n and adds it onto the accumulator arriving from rank
    r-1 (which computed the same chunk's partial last step) — the constraint
    c_s(r) = c_{s-1}(r-1) pins the schedule. After n steps rank r holds row
    chunk r fully reduced; a ring all-gather reassembles [t, out]. Each
    ppermute overlaps the next chunk's partial matmul.
    """
    r = lax.axis_index(axis_name)
    t = x.shape[0]
    tc = t // n
    fwd = [(i, (i + 1) % n) for i in range(n)]
    acc = None
    for s in range(n):
        if s > 0:
            with _obs.comm_span("tp_ring_allreduce.hop",
                                nbytes=acc.size * acc.dtype.itemsize):
                acc = lax.ppermute(acc, axis_name, fwd)
        c = (r - s - 1) % n
        rows = lax.dynamic_slice_in_dim(x, c * tc, tc, 0)
        with jax.named_scope("tp_ring_allreduce.partial_matmul"):
            part = rows @ w
        acc = part if acc is None else acc + part
    out = jnp.zeros((t,) + acc.shape[1:], acc.dtype)
    out = lax.dynamic_update_slice_in_dim(out, acc, r * tc, 0)
    buf = acc
    for h in range(1, n):
        with _obs.comm_span("tp_ring_allreduce.gather_hop",
                            nbytes=buf.size * buf.dtype.itemsize):
            buf = lax.ppermute(buf, axis_name, fwd)
        out = lax.dynamic_update_slice_in_dim(out, buf, ((r - h) % n) * tc, 0)
    return out


def _rar_fwd(x, w, n, axis_name):
    return ring_allreduce_matmul(x, w, n, axis_name), (x, w)


def _rar_bwd(n, axis_name, res, g):
    # shard_map (check_rep/vma off) hands an mp-replicated output's cotangent
    # back DIVIDED by the mp size; the blocking psum(x @ w) backward restores
    # it through its psum transpose. Issue the identical psum so both paths
    # run the same ops bitwise, then both grads are local matmuls.
    x, w = res
    g = lax.psum(g, axis_name)
    return g @ w.T, x.T @ g


ring_allreduce_matmul.defvjp(_rar_fwd, _rar_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def ring_allgather_matmul(x, w, n, axis_name):
    """Column-parallel matmul with the output all-gather decomposed into a
    chunked pipeline.

    x: [t, k] replicated, w: [k, out/n] local shard -> [t, out] gathered.

    The local column block is computed in n row chunks; as soon as chunk c's
    [t/n, out/n] block is done it starts riding the ring (n-1 hops to reach
    everyone) while chunk c+1's matmul runs — the hops carry no data
    dependence on later chunks, so the scheduler overlaps transfer with
    compute. Per-device FLOPs and bytes moved are identical to the fused
    path, and every output element is produced by the same x @ w_shard
    product on its owning rank, so the result is bitwise identical to
    matmul + all-gather at ANY degree.
    """
    r = lax.axis_index(axis_name)
    t = x.shape[0]
    tc = t // n
    nc = w.shape[1]
    fwd = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((t, nc * n), jnp.result_type(x.dtype, w.dtype))
    for c in range(n):
        rows = lax.dynamic_slice_in_dim(x, c * tc, tc, 0)
        with jax.named_scope("tp_ring_allgather.partial_matmul"):
            buf = rows @ w
        row0 = jnp.asarray(c * tc, r.dtype)
        out = lax.dynamic_update_slice(out, buf, (row0, r * nc))
        for h in range(1, n):
            with _obs.comm_span("tp_ring_allgather.hop",
                                nbytes=buf.size * buf.dtype.itemsize):
                buf = lax.ppermute(buf, axis_name, fwd)
            out = lax.dynamic_update_slice(
                out, buf, (row0, ((r - h) % n) * nc))
    return out


def _rag_fwd(x, w, n, axis_name):
    return ring_allgather_matmul(x, w, n, axis_name), (x, w)


def _rag_bwd(n, axis_name, res, g):
    # blocking backward of all_gather(x @ w, tiled): the gather transpose is a
    # psum_scatter — psum the (1/n-scaled, see _rar_bwd) cotangent and slice
    # the rank's own column block. dx stays per-rank partial; the shard_map
    # boundary transpose psums it over mp (x is unmentioned there), exactly as
    # it does for the blocking path.
    x, w = res
    r = lax.axis_index(axis_name)
    nc = w.shape[1]
    g_loc = lax.dynamic_slice_in_dim(lax.psum(g, axis_name), r * nc, nc, 1)
    dx = g_loc @ w.T
    dw = x.T @ g_loc
    return dx, dw


ring_allgather_matmul.defvjp(_rag_fwd, _rag_bwd)


# blocking references (same island layout, fused collective) — the parity
# baseline the ring kernels must match bit-for-bit at degree 2
def blocking_allreduce_matmul(x, w, n, axis_name):
    y = x @ w
    with _obs.comm_span("tp_blocking.allreduce",
                        nbytes=y.size * y.dtype.itemsize):
        return lax.psum(y, axis_name)


def blocking_allgather_matmul(x, w, n, axis_name):
    y = x @ w
    with _obs.comm_span("tp_blocking.allgather",
                        nbytes=y.size * y.dtype.itemsize):
        return lax.all_gather(y, axis_name, axis=1, tiled=True)


# ---------------------------------------------------------------------------
# GSPMD embedding: fully-manual islands callable from hint-traced layer code
# ---------------------------------------------------------------------------

def _batch_axis_spec(mesh, t, batch_axis):
    """Shard the flattened token dim over ``batch_axis`` when it divides
    cleanly (keeps a dp-sharded batch in place); replicate otherwise."""
    if batch_axis and batch_axis in mesh.shape and mesh.shape[batch_axis] > 1 \
            and t % mesh.shape[batch_axis] == 0:
        return batch_axis
    return None


def _island(mesh, body, n, mp_axis, x_spec, w_spec, out_spec):
    return shard_map(functools.partial(body, n=n, axis_name=mp_axis),
                     mesh=mesh, in_specs=(x_spec, w_spec),
                     out_specs=out_spec, axis_names=frozenset(mesh.axis_names),
                     check_vma=False)


def plan_row_parallel(x_shape, w_shape, mesh, mp_axis="mp", batch_axis="dp",
                      kernel=ring_allreduce_matmul):
    """Overlapped row-parallel linear: x [..., k] (k sharded over mp),
    w [k, out] -> [..., out] replicated over mp. Returns an apply(x, w)
    closure, or None when the overlap doesn't apply (caller falls back to
    the fused GSPMD path)."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    k, out_f = w_shape
    if x_shape[-1] != k or k % n:
        return None
    t = 1
    for d in x_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // (mesh.shape[bax] if bax else 1)
    # ring chunks are rows of the LOCAL token block
    if t_loc % n or t_loc // n < min_chunk():
        return None
    f = _island(mesh, kernel, n, mp_axis,
                P(bax, mp_axis), P(mp_axis, None), P(bax, None))
    _obs.record_counter("tp.row_parallel.plans")

    def apply(x, w):
        out = f(x.reshape(t, k), w)
        return out.reshape(tuple(x_shape[:-1]) + (out_f,))

    return apply


def plan_column_parallel(x_shape, w_shape, mesh, mp_axis="mp",
                         batch_axis="dp", kernel=ring_allgather_matmul):
    """Overlapped column-parallel linear with gathered output: x [..., k]
    replicated, w [k, out] (out sharded over mp) -> [..., out] gathered.
    Returns an apply(x, w) closure, or None when the overlap doesn't apply."""
    n = mesh.shape.get(mp_axis, 1)
    if n <= 1:
        return None
    k, out_f = w_shape
    if x_shape[-1] != k or out_f % n or out_f // n < min_chunk():
        return None
    t = 1
    for d in x_shape[:-1]:
        t *= d
    bax = _batch_axis_spec(mesh, t, batch_axis)
    t_loc = t // (mesh.shape[bax] if bax else 1)
    # pipeline chunks are row blocks of the LOCAL token dim
    if t_loc % n or t_loc // n < min_chunk():
        return None
    f = _island(mesh, kernel, n, mp_axis,
                P(bax, None), P(None, mp_axis), P(bax, None))
    _obs.record_counter("tp.column_parallel.plans")

    def apply(x, w):
        out = f(x.reshape(t, k), w)
        return out.reshape(tuple(x_shape[:-1]) + (out_f,))

    return apply


def overlap_row_parallel(x, w, mesh, **kwargs):
    plan = plan_row_parallel(x.shape, w.shape, mesh, **kwargs)
    return None if plan is None else plan(x, w)


def overlap_column_parallel(x, w, mesh, **kwargs):
    plan = plan_column_parallel(x.shape, w.shape, mesh, **kwargs)
    return None if plan is None else plan(x, w)
