"""Expert-parallel MoE dispatch/combine over the 'ep' mesh axis.

Ref: python/paddle/incubate/distributed/models/moe/moe_layer.py +
global_scatter/global_gather collective ops. The reference dispatches tokens
with capacity-bucketed all-to-all (brpc/NCCL global_scatter). TPU-native:
capacity-bucketed one-hot dispatch expressed as einsums — GSPMD turns the
expert-sharded einsum into the all-to-all over ICI — plus an explicit
shard_map path (moe_shard_map_dispatch) for when the schedule must be manual.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def top_k_gating(logits, k: int, capacity: int):
    """gshard/switch gating. logits [T, E] fp32. Returns (combine [T, E, C],
    dispatch [T, E, C] bool, aux_loss scalar)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    gates = jnp.zeros_like(probs)
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        gates = gates + onehot * probs
        remaining = remaining * (1 - onehot)

    # aux load-balancing loss (gshard): E * mean(fraction_tokens * mean_prob)
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=probs.dtype), axis=0)
    aux_loss = E * jnp.sum(me * ce)

    # capacity assignment: position of each token within its expert queue
    chosen = gates > 0  # [T, E]
    position_in_expert = (jnp.cumsum(chosen, axis=0) - 1) * chosen  # [T, E]
    in_capacity = chosen & (position_in_expert < capacity)
    pos_oh = jax.nn.one_hot(position_in_expert, capacity, dtype=probs.dtype)  # [T,E,C]
    dispatch = pos_oh * in_capacity[..., None]
    combine = dispatch * gates[..., None]
    # renormalize combine weights over selected experts
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9) * gates.sum(-1)[:, None, None]
    return combine, dispatch, aux_loss


def moe_dispatch_combine(x, gate_logits, expert_fn, expert_params, num_experts,
                         k=2, capacity_factor=1.25):
    """GSPMD MoE: x [T, D] tokens, expert_params stacked [E, ...] (shard the
    leading axis over 'ep' with PartitionSpec). The dispatch einsum produces
    [E, C, D] which GSPMD all-to-alls to the expert owners."""
    T, D = x.shape
    capacity = int(capacity_factor * T * k / num_experts + 1)
    combine, dispatch, aux = top_k_gating(gate_logits, k, capacity)
    # [T,E,C] x [T,D] -> [E,C,D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in)  # [E,C,D']
    out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype), expert_out)
    return out, aux


def moe_shard_map_dispatch(x, gate_logits, expert_fn, expert_params_local,
                           num_experts, axis_name="ep", k=2,
                           capacity_factor=1.25):
    """Explicit all-to-all path (inside shard_map over 'ep'): each device owns
    E/ep experts; tokens route via lax.all_to_all, mirroring the reference's
    global_scatter/global_gather."""
    n = lax.axis_size(axis_name)
    T, D = x.shape  # T = this device's LOCAL tokens
    e_local = num_experts // n
    capacity = int(capacity_factor * T * k / num_experts + 1)
    combine, dispatch, aux = top_k_gating(gate_logits, k, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E,C,D]
    # tiled all_to_all: expert axis (owner-major: expert e lives on device
    # e // e_local) splits into n chunks of e_local experts, received chunks
    # concatenate along capacity -> each owner holds its experts' slots from
    # EVERY source device: [e_local, n*C, D]
    recv = lax.all_to_all(expert_in, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    out_local = jax.vmap(expert_fn)(expert_params_local, recv)
    # inverse exchange: capacity splits back per source, experts concat back
    # to the full [E, C, D'] on each source device
    expert_out = lax.all_to_all(out_local, axis_name, split_axis=1,
                                concat_axis=0, tiled=True)
    out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype), expert_out)
    return out, aux
